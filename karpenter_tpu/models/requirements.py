"""Label-requirement set algebra.

This is the constraint engine of the whole framework — the reference's
`scheduling.Requirements` (sigs.k8s.io/karpenter/pkg/scheduling; behavior
documented at website/content/en/preview/concepts/nodepools.md:240-304 and
exercised via the NodePool CRD `spec.template.spec.requirements` —
pkg/apis/crds/karpenter.sh_nodepools.yaml).

A `Requirement` is, per label key, a (possibly complemented) value set plus
optional integer bounds:

  In [a,b]        vals={a,b}, complement=False
  NotIn [a,b]     vals={a,b}, complement=True
  Exists          vals={},    complement=True,  requires existence
  DoesNotExist    vals={},    complement=False  (allowed set empty, absent ok)
  Gt n / Lt n     complement=True + integer bound, requires existence

Set intersection follows the standard complement algebra; bounds tighten by
max(gt) / min(lt). `requires_existence` is tracked separately so that
closed-world matching against a concrete node's labels can honor k8s
node-affinity semantics (NotIn / DoesNotExist match a missing label; In /
Exists / Gt / Lt do not).

`min_values` carries the NodePool `minValues` field (per-key floor on the
number of distinct values among the instance types chosen for a claim —
nodepools.md:240-304); it is enforced at instance-type selection time, not in
the set algebra.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, Iterator, Optional


class Operator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


class Requirement:
    __slots__ = ("key", "vals", "complement", "greater_than", "less_than",
                 "requires_existence", "min_values", "_h")

    def __init__(
        self,
        key: str,
        vals: Iterable[str] = (),
        complement: bool = False,
        greater_than: Optional[int] = None,
        less_than: Optional[int] = None,
        requires_existence: bool = True,
        min_values: Optional[int] = None,
    ):
        self.key = key
        self.vals = frozenset(vals)
        self.complement = complement
        self.greater_than = greater_than
        self.less_than = less_than
        self.requires_existence = requires_existence
        self.min_values = min_values
        self._h: Optional[int] = None  # Requirement is immutable; hash cached

    # -- constructors ----------------------------------------------------
    @classmethod
    def make(cls, key: str, op: "Operator | str", *vals: str,
             min_values: Optional[int] = None) -> "Requirement":
        op = Operator(op)
        svals = [str(v) for v in vals]
        if op is Operator.IN:
            return cls(key, svals, min_values=min_values)
        if op is Operator.NOT_IN:
            return cls(key, svals, complement=True, requires_existence=False)
        if op is Operator.EXISTS:
            return cls(key, (), complement=True)
        if op is Operator.DOES_NOT_EXIST:
            return cls(key, (), complement=False, requires_existence=False)
        if op is Operator.GT:
            return cls(key, (), complement=True, greater_than=int(svals[0]))
        if op is Operator.LT:
            return cls(key, (), complement=True, less_than=int(svals[0]))
        raise ValueError(op)

    @classmethod
    def single(cls, key: str, value: str) -> "Requirement":
        """A node label: key In [value]."""
        return cls(key, (value,))

    # -- predicates ------------------------------------------------------
    def _in_bounds(self, value: str) -> bool:
        if self.greater_than is None and self.less_than is None:
            return True
        try:
            n = int(value)
        except ValueError:
            return False
        if self.greater_than is not None and not n > self.greater_than:
            return False
        if self.less_than is not None and not n < self.less_than:
            return False
        return True

    def matches(self, value: str) -> bool:
        """Does a concrete label value satisfy this requirement?"""
        if not self._in_bounds(value):
            return False
        if self.complement:
            return value not in self.vals
        return value in self.vals

    def matches_absent(self) -> bool:
        """Does a node *without* this label satisfy this requirement?"""
        return not self.requires_existence

    def is_empty(self) -> bool:
        """No concrete value can ever satisfy this requirement. Note a
        requirement may be empty yet still satisfiable by *absence*
        (DoesNotExist) — see is_unsatisfiable().
        """
        if not self.complement:
            if not self.vals:
                return True  # DoesNotExist-shaped: empty allowed set
            return not any(self._in_bounds(v) for v in self.vals)
        if self.greater_than is not None and self.less_than is not None:
            return self.greater_than + 1 > self.less_than - 1
        return False

    def is_unsatisfiable(self) -> bool:
        """Nothing — no concrete value and not even label absence — can
        satisfy this requirement.
        """
        return self.is_empty() and not self.matches_absent()

    def values(self) -> frozenset[str]:
        """Concrete allowed values (only meaningful for non-complement sets)."""
        if self.complement:
            raise ValueError(f"requirement on {self.key} has no finite value set")
        return frozenset(v for v in self.vals if self._in_bounds(v))

    def is_finite(self) -> bool:
        return not self.complement

    # -- algebra ---------------------------------------------------------
    def intersect(self, other: "Requirement") -> "Requirement":
        assert self.key == other.key
        gt = max(
            (x for x in (self.greater_than, other.greater_than) if x is not None),
            default=None,
        )
        lt = min(
            (x for x in (self.less_than, other.less_than) if x is not None),
            default=None,
        )
        if self.complement and other.complement:
            vals, comp = self.vals | other.vals, True
        elif not self.complement and not other.complement:
            vals, comp = self.vals & other.vals, False
        elif not self.complement:
            vals, comp = self.vals - other.vals, False
        else:
            vals, comp = other.vals - self.vals, False
        mv_candidates = [x for x in (self.min_values, other.min_values) if x is not None]
        return Requirement(
            self.key, vals, comp, gt, lt,
            requires_existence=self.requires_existence or other.requires_existence,
            min_values=max(mv_candidates) if mv_candidates else None,
        )

    def intersects(self, other: "Requirement") -> bool:
        return not self.intersect(other).is_unsatisfiable()

    # -- misc ------------------------------------------------------------
    def _identity(self):
        return (self.key, self.vals, self.complement, self.greater_than,
                self.less_than, self.requires_existence, self.min_values)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Requirement) and self._identity() == other._identity()

    def __hash__(self) -> int:
        if self._h is None:
            self._h = hash(self._identity())
        return self._h

    def __repr__(self) -> str:
        if self.complement and not self.vals and self.greater_than is None \
                and self.less_than is None:
            body = "Exists"
        elif self.complement and self.vals:
            body = f"NotIn{sorted(self.vals)}"
        elif not self.complement and not self.vals:
            body = "DoesNotExist"
        else:
            body = f"In{sorted(self.vals)}"
        if self.greater_than is not None:
            body += f" >{self.greater_than}"
        if self.less_than is not None:
            body += f" <{self.less_than}"
        return f"Req({self.key} {body})"


class Requirements:
    """A conjunction of per-key Requirements, with open-world semantics:
    a key not present is unconstrained (any value, or absent).

    Mirrors sigs.k8s.io/karpenter/pkg/scheduling.Requirements: NewRequirements,
    Add (intersect-in-place), Compatible (pairwise nonempty intersection over
    shared keys), Intersects.
    """

    __slots__ = ("_reqs", "_hash", "_sat")

    def __init__(self, *reqs: Requirement):
        self._reqs: Dict[str, Requirement] = {}
        self._hash: Optional[int] = None
        # memoized "no key is unsatisfiable" verdict: compatible() re-scans
        # every own requirement per call, and instance-type requirement sets
        # are immutable in practice — the oracle's per-(pod×type) checks
        # were ~1M is_unsatisfiable calls per 5k-pod solve without this
        self._sat: Optional[bool] = None
        for r in reqs:
            self.add(r)

    @classmethod
    def from_labels(cls, labels: "Dict[str, str]") -> "Requirements":
        return cls(*(Requirement.single(k, v) for k, v in labels.items()))

    @classmethod
    def from_node_selector(cls, selector: "Dict[str, str]") -> "Requirements":
        return cls(*(Requirement.single(k, v) for k, v in selector.items()))

    # -- container protocol ---------------------------------------------
    def __iter__(self) -> Iterator[Requirement]:
        return iter(self._reqs.values())

    def __len__(self) -> int:
        return len(self._reqs)

    def __contains__(self, key: str) -> bool:
        return key in self._reqs

    def get(self, key: str) -> Optional[Requirement]:
        return self._reqs.get(key)

    def keys(self):
        return self._reqs.keys()

    # -- mutation --------------------------------------------------------
    def add(self, req: Requirement) -> None:
        """Tighten: intersect with any existing requirement on the same key."""
        cur = self._reqs.get(req.key)
        self._reqs[req.key] = cur.intersect(req) if cur is not None else req
        self._hash = None
        self._sat = None

    def update(self, other: "Requirements") -> None:
        for r in other:
            self.add(r)

    def copy(self) -> "Requirements":
        out = Requirements()
        out._reqs = dict(self._reqs)
        out._hash = self._hash
        out._sat = self._sat
        return out

    # -- algebra ---------------------------------------------------------
    def intersection(self, other: "Requirements") -> "Requirements":
        out = self.copy()
        out.update(other)
        return out

    def compatible(self, other: "Requirements") -> bool:
        """Open-world compatibility: every shared key's intersection is
        nonempty and no key becomes unsatisfiable. A key present on only one
        side is unconstrained on the other (the missing side can still take
        any value) — this is how a NodePool template that says nothing about
        `zone` remains compatible with a pod that pins a zone.
        """
        for key, req in other._reqs.items():
            cur = self._reqs.get(key)
            if cur is None:
                if req.is_unsatisfiable():
                    return False
                continue
            if not cur.intersects(req):
                return False
        if self._sat is None:
            self._sat = not any(
                r.is_unsatisfiable() for r in self._reqs.values())
        return self._sat

    def conflict_key(self, other: "Requirements") -> Optional[str]:
        """First key whose intersection is empty, for error messages."""
        for key, req in other._reqs.items():
            cur = self._reqs.get(key)
            if cur is not None and not cur.intersects(req):
                return key
            if cur is None and req.is_unsatisfiable():
                return key
        for key, r in self._reqs.items():
            if r.is_unsatisfiable():
                return key
        return None

    # -- closed-world matching (concrete node labels) --------------------
    def matched_by_labels(self, labels: "Dict[str, str]") -> bool:
        """k8s node-affinity semantics against a concrete label set: every
        requirement must be satisfied by the node's value for the key, or —
        if the label is absent — the requirement must tolerate absence
        (NotIn / DoesNotExist).
        """
        for key, req in self._reqs.items():
            val = labels.get(key)
            if val is None:
                if not req.matches_absent():
                    return False
            elif not req.matches(val):
                return False
        return True

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Requirements) and self._reqs == other._reqs

    def __hash__(self) -> int:
        if self._hash is None:
            self._hash = hash(frozenset(self._reqs.values()))
        return self._hash

    def __repr__(self) -> str:
        return f"Requirements({', '.join(map(repr, self._reqs.values()))})"
