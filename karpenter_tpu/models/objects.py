"""CRD-shaped objects.

NodePool / NodeClaim mirror the core CRDs
(pkg/apis/crds/karpenter.sh_nodepools.yaml, karpenter.sh_nodeclaims.yaml);
NodeClass is the provider CRD analogue of EC2NodeClass
(pkg/apis/v1/ec2nodeclass.go:29-128) with TPU/GCE-shaped fields; InstanceType
and Offering mirror cloudprovider.InstanceType
(consumed at pkg/cloudprovider/cloudprovider.go:172-193 and built by
pkg/providers/instancetype/types.go:51-210).
"""

from __future__ import annotations

import hashlib
import itertools
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.models import wellknown
from karpenter_tpu.models.requirements import Requirement, Requirements
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.models.taints import Taint, Toleration

_uid_counter = itertools.count(1)
_SCHED_KEY_INTERN: Dict[tuple, int] = {}
_INTERN_LIMIT = 100_000
# group ids are globally unique (never reused across intern-table resets)
_sched_gid_counter = itertools.count(1)


def do_not_disrupt(meta: "ObjectMeta") -> bool:
    """The karpenter.sh/do-not-disrupt annotation — ONE definition for
    every level it applies at (pod, node, nodeclaim)."""
    return meta.annotations.get(wellknown.DO_NOT_DISRUPT_ANNOTATION) == "true"


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    creation_time: float = 0.0
    deletion_time: Optional[float] = None  # set => being deleted (finalizing)
    resource_version: int = 0

    @property
    def deleting(self) -> bool:
        return self.deletion_time is not None


# ---------------------------------------------------------------------------
# Workloads
# ---------------------------------------------------------------------------

@dataclass
class TopologySpreadConstraint:
    topology_key: str
    max_skew: int = 1
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Dict[str, str] = field(default_factory=dict)
    min_domains: Optional[int] = None


@dataclass
class PodAffinityTerm:
    """Required/preferred pod (anti-)affinity over a topology domain."""
    label_selector: Dict[str, str]
    topology_key: str
    anti: bool = False
    required: bool = True
    weight: int = 100  # for preferred terms
    # True on the required=True copy the relaxation ladder makes of a
    # preferred term: enforced for the pod's own placement, but excluded
    # from the k8s anti-affinity SYMMETRY rule — a soft anti must never
    # hard-block other pods (scheduling.md:282-379 scoring semantics)
    promoted: bool = False


@dataclass
class VolumeClaim:
    """A persistent-volume claim a pod mounts (PV topology —
    scheduling.md:381-417): once bound to a zonal volume, the pod can only
    schedule into that zone, and each claim consumes one of the node's
    attachable-volume slots (the `volumes` resource axis). An unbound
    claim (WaitForFirstConsumer) binds to whatever zone the scheduler
    picks — the binder stamps it at bind time."""
    name: str
    zone: Optional[str] = None      # set once bound to a zonal volume
    bound: bool = False
    storage_class: str = "standard"


@dataclass
class Pod:
    meta: ObjectMeta
    requests: Resources = field(default_factory=Resources)
    # hard node constraints: nodeSelector + requiredDuringScheduling node
    # affinity, already folded into one Requirements conjunction
    requirements: Requirements = field(default_factory=Requirements)
    # preferredDuringScheduling node affinity: (weight, requirements) terms
    preferences: List[Tuple[int, Requirements]] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread: List[TopologySpreadConstraint] = field(default_factory=list)
    pod_affinities: List[PodAffinityTerm] = field(default_factory=list)
    # persistent-volume claims this pod mounts (attach slots + zone pinning)
    volume_claims: List[VolumeClaim] = field(default_factory=list)
    priority: int = 0
    # k8s priorityClassName — resolved to an integer through
    # scheduling.types.PRIORITY_CLASSES by priority_of (ISSUE 16)
    priority_class_name: Optional[str] = None
    # binding / lifecycle
    node_name: Optional[str] = None
    phase: str = "Pending"
    # "has a controller owner" — pods without one block consolidation
    # (designs/consolidation.md:46-52)
    owner_kind: Optional[str] = "ReplicaSet"
    is_daemonset: bool = False

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def scheduled(self) -> bool:
        return self.node_name is not None

# class attrs (deliberately unannotated: not dataclass fields)
    _sched_key_cache = None
    _sched_group_id = None

    def deletion_cost(self) -> float:
        raw = self.meta.annotations.get(wellknown.POD_DELETION_COST_ANNOTATION)
        try:
            return float(raw) if raw is not None else 0.0
        except ValueError:
            return 0.0

    def do_not_disrupt(self) -> bool:
        return do_not_disrupt(self.meta)

    def _soft_ladder(self) -> list:
        """Every best-effort term, strongest first: preferred node affinity
        (by weight), preferred pod (anti-)affinity (by weight), and
        ScheduleAnyway topology spread (weakest — pure scoring in kube).
        The relaxation loop drops them from the END of this list."""
        terms = []
        for i, (w, reqs) in enumerate(self.preferences):
            terms.append((w, 2, i, ("pref", reqs)))
        for i, t in enumerate(self.pod_affinities):
            if not t.required:
                terms.append((t.weight, 1, i, ("aff", t)))
        for i, c in enumerate(self.topology_spread):
            if c.when_unsatisfiable == "ScheduleAnyway":
                terms.append((0, 0, i, ("spread", c)))
        terms.sort(key=lambda x: (-x[0], -x[1], x[2]))
        return terms

    def relax_levels(self) -> int:
        """How many relaxation steps this pod supports (0 = nothing soft)."""
        return len(self._soft_ladder())

    def has_soft_terms(self) -> bool:
        if self.preferences:
            return True
        for t in self.pod_affinities:
            if not t.required:
                return True
        for c in self.topology_spread:
            if c.when_unsatisfiable == "ScheduleAnyway":
                return True
        return False

    def relaxed(self, level: int) -> "Pod":
        """The pod with its soft terms ENFORCED as hard constraints, the
        `level` weakest dropped entirely.

        Mirrors the reference scheduler's preference handling
        (website/content/en/preview/concepts/scheduling.md:282-379:
        preferences are treated as required, then relaxed one at a time
        when the pod cannot schedule). Enforcement per kind: preferred node
        affinity folds into the hard requirements; preferred pod
        (anti-)affinity becomes a required term; ScheduleAnyway spread
        becomes DoNotSchedule. level 0 = all enforced; level ==
        relax_levels() = none (the pod's true hard constraints only).
        Returns a variant with `preferences=[]` so variants at equal
        effective constraints share a scheduling group.
        """
        ladder = self._soft_ladder()
        if not ladder:
            return self
        import dataclasses
        keep = ladder[: max(0, len(ladder) - level)]
        eff = self.requirements
        affs = [t for t in self.pod_affinities if t.required]
        spreads = [c for c in self.topology_spread
                   if c.when_unsatisfiable != "ScheduleAnyway"]
        for _, _, _, (kind, payload) in keep:
            if kind == "pref":
                eff = eff.intersection(payload)
            elif kind == "aff":
                affs.append(dataclasses.replace(payload, required=True,
                                                promoted=True))
            else:
                spreads.append(dataclasses.replace(
                    payload, when_unsatisfiable="DoNotSchedule"))
        return dataclasses.replace(self, requirements=eff, preferences=[],
                                   pod_affinities=affs,
                                   topology_spread=spreads)

    def scheduling_key(self) -> tuple:
        """Equivalence-class key: pods with equal keys are interchangeable to
        the scheduler. The reference exploits the same equivalence when
        batching identical pods; the TPU grouped solver depends on it.
        Cached — pod specs are immutable once submitted for scheduling.
        """
        if self._sched_key_cache is not None:
            return self._sched_key_cache
        self._sched_key_cache = (
            self.requests,
            self.requirements,
            tuple(sorted(self.tolerations, key=str)),
            tuple(
                (c.topology_key, c.max_skew, c.when_unsatisfiable,
                 tuple(sorted(c.label_selector.items())), c.min_domains)
                for c in self.topology_spread
            ),
            tuple(
                (t.topology_key, t.anti, t.required,
                 tuple(sorted(t.label_selector.items())))
                for t in self.pod_affinities
            ),
            # preferred node affinity participates in relaxation (pods at
            # different relax states are not interchangeable)
            tuple((w, r) for w, r in self.preferences),
            # attach-slot count and bound zones change the packing
            # footprint and the zone mask respectively
            tuple(sorted((c.zone or "", c.bound)
                         for c in self.volume_claims)),
            tuple(sorted(self.meta.labels.items())),
            self.priority,
            self.is_daemonset,
            # gang identity (ISSUE 15): a gang member is NOT
            # interchangeable with an identical non-gang pod (its
            # placement is atomic with its gang), and two gangs never
            # share a class — the grouped solver's gang unit IS the
            # equivalence class.  None (inert) when the
            # KARPENTER_TPU_GANG rollback knob is off.
            self._gang_key(),
            # priority identity (ISSUE 16): beyond the spec `priority`
            # field above, the class/annotation-resolved effective
            # priority joins the key — two otherwise-identical pods in
            # different priority bands pack in different passes and must
            # not share a group.  None (inert) when the
            # KARPENTER_TPU_PRIORITY rollback knob is off or nothing
            # beyond the spec field contributes, keeping priority-free
            # keys bit-compatible with the pre-priority layout.
            self._priority_key(),
        )
        return self._sched_key_cache

    def _gang_key(self):
        # delegate to gang_of — the ONE owner of the annotation
        # grammar (knob gate, size/domain normalization): raw
        # annotation strings here would split one gang into two
        # classes on a cosmetic difference ("slice" vs "Slice") that
        # gang_of parses identically, and _encode_gang would then
        # reject the gang as multi-class.  Lazy import (the same
        # direction gang_of's own lazy imports take) avoids the
        # models↔scheduling cycle.
        from karpenter_tpu.scheduling.types import gang_of
        sp = gang_of(self)
        if sp is None:
            return None
        return (sp.name, sp.size, sp.domain_key)

    def _priority_key(self):
        # delegate to priority_of — the ONE owner of the priority
        # grammar (knob gate, annotation > class > spec precedence,
        # malformed-value degradation).  Only the EXTRA identity is
        # keyed: when the effective priority equals the spec field (the
        # priority-free common case, or the knob off) this is None and
        # the key layout matches the pre-priority one.
        from karpenter_tpu.scheduling.types import priority_of
        eff = priority_of(self)
        if eff == self.priority:
            return None
        return eff

    def scheduling_group_id(self) -> int:
        """Interned integer id of the scheduling_key — deep-tuple hashing is
        the grouping hot path at 50k pods, so equal keys are mapped to one
        int once per pod and grouped by int thereafter. Pod specs must not
        mutate after this is first called (k8s pod specs are immutable
        post-admission; the cache relies on it). The intern table is bounded:
        it resets once it exceeds _INTERN_LIMIT distinct keys — group ids
        from different epochs are never mixed because pods cache their id.
        """
        if self._sched_group_id is None:
            if len(_SCHED_KEY_INTERN) > _INTERN_LIMIT:
                _SCHED_KEY_INTERN.clear()
            key = self.scheduling_key()
            gid = _SCHED_KEY_INTERN.get(key)
            if gid is None:
                gid = next(_sched_gid_counter)
                _SCHED_KEY_INTERN[key] = gid
            self._sched_group_id = gid
        return self._sched_group_id


@dataclass
class PodDisruptionBudget:
    """Minimal PDB: how many pods matching the selector may be voluntarily
    disrupted (reference consumes these through the Eviction API —
    website/.../disruption.md:29-36; pods at/over budget block consolidation,
    designs/consolidation.md:46-52)."""
    meta: ObjectMeta
    selector: Dict[str, str] = field(default_factory=dict)
    max_unavailable: int = 1

    def matches(self, pod: "Pod") -> bool:
        return all(pod.meta.labels.get(k) == v for k, v in self.selector.items())


# ---------------------------------------------------------------------------
# Instance types
# ---------------------------------------------------------------------------

@dataclass
class Offering:
    """One purchasable (zone × capacity-type) variant of an instance type with
    a price (reference: createOfferings,
    pkg/providers/instancetype/instancetype.go:264-315).
    """
    zone: str
    capacity_type: str
    price: float
    available: bool = True

    def requirements(self) -> Requirements:
        return Requirements(
            Requirement.single(wellknown.ZONE_LABEL, self.zone),
            Requirement.single(wellknown.CAPACITY_TYPE_LABEL, self.capacity_type),
        )


@dataclass
class InstanceType:
    """A machine shape: capacity, overhead, static label requirements, and
    offerings (reference: cloudprovider.InstanceType built at
    pkg/providers/instancetype/types.go:51-210).
    """
    name: str
    capacity: Resources
    requirements: Requirements  # single-valued label reqs + zone/captype In[...]
    offerings: List[Offering] = field(default_factory=list)
    overhead: Resources = field(default_factory=Resources)  # kube-reserved + eviction

    _allocatable: Optional[Resources] = field(default=None, repr=False, compare=False)

    def allocatable(self) -> Resources:
        if self._allocatable is None:
            self._allocatable = self.capacity - self.overhead
        return self._allocatable

    def available_offerings(self, reqs: Optional[Requirements] = None) -> List[Offering]:
        """Offerings compatible with the zone / capacity-type constraints in
        `reqs`. Only those two keys are consulted — other keys in `reqs`
        (arch, instance-type, …) are about the instance type itself, not the
        offering, and are open-world here (reference: offering filtering in
        pkg/cloudprovider/cloudprovider.go:276-281 checks offering
        requirements only).
        """
        zone_req = reqs.get(wellknown.ZONE_LABEL) if reqs is not None else None
        ct_req = reqs.get(wellknown.CAPACITY_TYPE_LABEL) if reqs is not None else None
        out = []
        for o in self.offerings:
            if not o.available:
                continue
            if zone_req is not None and not zone_req.matches(o.zone):
                continue
            if ct_req is not None and not ct_req.matches(o.capacity_type):
                continue
            out.append(o)
        return out

    def cheapest_offering(self, reqs: Optional[Requirements] = None) -> Optional[Offering]:
        offs = self.available_offerings(reqs)
        return min(offs, key=lambda o: o.price) if offs else None


# ---------------------------------------------------------------------------
# Nodes & claims
# ---------------------------------------------------------------------------

@dataclass
class Node:
    meta: ObjectMeta
    provider_id: Optional[str] = None
    capacity: Resources = field(default_factory=Resources)
    allocatable: Resources = field(default_factory=Resources)
    taints: List[Taint] = field(default_factory=list)
    ready: bool = False

    @property
    def name(self) -> str:
        return self.meta.name

    @property
    def labels(self) -> Dict[str, str]:
        return self.meta.labels

    @property
    def nodepool(self) -> Optional[str]:
        return self.meta.labels.get(wellknown.NODEPOOL_LABEL)

    @property
    def zone(self) -> Optional[str]:
        return self.meta.labels.get(wellknown.ZONE_LABEL)

    @property
    def capacity_type(self) -> Optional[str]:
        return self.meta.labels.get(wellknown.CAPACITY_TYPE_LABEL)

    @property
    def instance_type(self) -> Optional[str]:
        return self.meta.labels.get(wellknown.INSTANCE_TYPE_LABEL)


# NodeClaim status conditions (karpenter.sh_nodeclaims.yaml status.conditions;
# lifecycle per SURVEY §2.2 "Node lifecycle").
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"


@dataclass
class NodeClaim:
    meta: ObjectMeta
    nodepool: str
    node_class_ref: str
    # owning pool's UID, the k8s ownerReference analogue: GC cascades only
    # for claims whose owner UID no longer matches a live pool, so a
    # delete+recreate of a NodePool under the same name between GC passes
    # does not drain the recreated fleet
    nodepool_uid: Optional[str] = None
    requirements: Requirements = field(default_factory=Requirements)
    resource_requests: Resources = field(default_factory=Resources)  # aggregate of packed pods
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    # ranked candidate instance types (cheapest-first), as the reference's
    # NodeClaim carries instance-type requirements ranked by price
    instance_type_options: List[str] = field(default_factory=list)
    # max drain time before PDBs stop being honored, stamped from the
    # NodePool template at creation (reference: NodeClaim
    # spec.terminationGracePeriod) — read from the CLAIM, not the live
    # pool, so claims orphaned by pool deletion still force-drain
    termination_grace_period: Optional[float] = None
    # status
    provider_id: Optional[str] = None
    node_name: Optional[str] = None
    capacity: Resources = field(default_factory=Resources)
    allocatable: Resources = field(default_factory=Resources)
    conditions: Dict[str, bool] = field(default_factory=dict)
    launch_time: Optional[float] = None

    @property
    def name(self) -> str:
        return self.meta.name

    def is_(self, cond: str) -> bool:
        return self.conditions.get(cond, False)

    def set_condition(self, cond: str, val: bool = True) -> None:
        self.conditions[cond] = val


# ---------------------------------------------------------------------------
# NodePool & NodeClass
# ---------------------------------------------------------------------------

@dataclass
class Budget:
    """Disruption budget (karpenter.sh_nodepools.yaml spec.disruption.budgets).
    nodes: "10%" or "5"; reasons limits which disruption reasons it caps.
    """
    nodes: str = "10%"
    schedule: Optional[str] = None  # cron; None = always active
    duration: Optional[float] = None  # seconds the window stays open
    reasons: Optional[List[str]] = None  # None = all reasons

    def allowed_disruptions(self, total_nodes: int) -> int:
        if self.nodes.endswith("%"):
            import math
            pct = float(self.nodes[:-1]) / 100.0
            # ceil (with float-error guard): "10%" of a 3-node cluster allows
            # 1 disruption — flooring would freeze small clusters entirely
            return math.ceil(pct * total_nodes - 1e-9)
        return int(self.nodes)


CONSOLIDATE_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED = "WhenEmptyOrUnderutilized"
CONSOLIDATE_WHEN_UNDERUTILIZED = "WhenUnderutilized"


@dataclass
class Disruption:
    consolidation_policy: str = CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED
    consolidate_after: float = 0.0  # seconds; 0 = immediately
    budgets: List[Budget] = field(default_factory=lambda: [Budget(nodes="10%")])


@dataclass
class NodePool:
    """karpenter.sh/NodePool (karpenter.sh_nodepools.yaml): a template for
    nodes plus disruption policy, limits, and weight.
    """
    meta: ObjectMeta
    node_class_ref: str = "default"
    requirements: Requirements = field(default_factory=Requirements)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)       # template labels
    annotations: Dict[str, str] = field(default_factory=dict)
    expire_after: Optional[float] = None  # seconds; None = Never
    termination_grace_period: Optional[float] = None
    disruption: Disruption = field(default_factory=Disruption)
    limits: Optional[Resources] = None
    weight: int = 0  # higher = tried first (nodepools.md:525-529)

    @property
    def name(self) -> str:
        return self.meta.name

    def template_requirements(self) -> Requirements:
        """Full requirement set a node from this pool will satisfy."""
        reqs = Requirements.from_labels(self.labels)
        reqs.update(self.requirements)
        reqs.add(Requirement.single(wellknown.NODEPOOL_LABEL, self.name))
        return reqs

    def static_hash(self) -> str:
        """Drift-detection hash over the template's static fields
        (reference: NodePool hash annotation mechanism,
        pkg/controllers/nodeclass/hash/controller.go:48-128 analogue).
        """
        payload = json.dumps({
            "labels": sorted(self.labels.items()),
            "annotations": sorted(self.annotations.items()),
            "taints": sorted(str(t) for t in self.taints),
            "startup_taints": sorted(str(t) for t in self.startup_taints),
            "requirements": sorted(repr(r) for r in self.requirements),
            "node_class_ref": self.node_class_ref,
            "expire_after": self.expire_after,
        }, sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass
class SelectorTerm:
    """One discovery selector term (pkg/apis/v1/ec2nodeclass.go selector
    terms): terms in a list are OR'd; within a term, id/name/tags are AND'd
    and the tag map entries are AND'd."""
    id: Optional[str] = None
    name: Optional[str] = None
    tags: Dict[str, str] = field(default_factory=dict)

    def matches(self, obj_id: str, name: str = "",
                tags: Optional[Dict[str, str]] = None) -> bool:
        if self.id is not None and self.id != obj_id:
            return False
        if self.name is not None and self.name != name:
            return False
        tags = tags or {}
        for k, v in self.tags.items():
            if v == "*":
                if k not in tags:
                    return False
            elif tags.get(k) != v:
                return False
        return True

    def key(self) -> tuple:
        return (self.id, self.name, tuple(sorted(self.tags.items())))


def match_selector_terms(terms: List[SelectorTerm], obj_id: str,
                         name: str = "",
                         tags: Optional[Dict[str, str]] = None) -> bool:
    """Empty terms = select nothing is the reference's rule; our fake cloud
    seeds cluster-tagged defaults, so None/empty means 'cluster defaults'
    and is handled by the providers, not here."""
    return any(t.matches(obj_id, name, tags) for t in terms)


@dataclass
class BlockDevice:
    """Volume parameters for a block-device mapping
    (pkg/apis/v1/ec2nodeclass.go:319-382 BlockDevice). Sizes are GiB; the
    TPU cloud's volume types mirror the reference's enum so selector
    semantics carry over."""
    volume_size_gib: Optional[int] = None
    volume_type: str = "gp3"
    iops: Optional[int] = None
    throughput: Optional[int] = None
    encrypted: bool = True
    kms_key_id: Optional[str] = None
    snapshot_id: Optional[str] = None
    delete_on_termination: bool = True

    def key(self) -> tuple:
        return (self.volume_size_gib, self.volume_type, self.iops,
                self.throughput, self.encrypted, self.kms_key_id,
                self.snapshot_id, self.delete_on_termination)


@dataclass
class BlockDeviceMapping:
    """One device attach (pkg/apis/v1/ec2nodeclass.go:305-317): a list of
    these, not a single scalar GiB — the root volume (at most one) sizes
    the node's ephemeral-storage capacity."""
    device_name: str
    ebs: BlockDevice = field(default_factory=BlockDevice)
    root_volume: bool = False

    def key(self) -> tuple:
        return (self.device_name, self.ebs.key(), self.root_volume)


@dataclass
class MetadataOptions:
    """Instance metadata service exposure
    (pkg/apis/v1/ec2nodeclass.go:255-300). Defaults mirror the
    reference's hardened defaults (IMDSv2-style required tokens,
    hop limit 1)."""
    http_endpoint: str = "enabled"      # enabled | disabled
    http_protocol_ipv6: str = "disabled"
    http_put_response_hop_limit: int = 1
    http_tokens: str = "required"       # required | optional

    def key(self) -> tuple:
        return (self.http_endpoint, self.http_protocol_ipv6,
                self.http_put_response_hop_limit, self.http_tokens)


# instance-store policy enum (pkg/apis/v1/ec2nodeclass.go:384-394): RAID0
# stripes all local NVMe disks into the node's ephemeral storage
INSTANCE_STORE_RAID0 = "RAID0"


@dataclass
class KubeletConfiguration:
    """Per-NodeClass kubelet args (pkg/apis/v1/ec2nodeclass.go:186-253),
    the subset that feeds allocatable math: max-pods / pods-per-core
    override the catalog's ENI-style ladder; reserved and eviction maps
    override the reserved-resource formulas
    (pkg/providers/instancetype/types.go:363-431). Quantities are
    k8s-style strings ("100m", "1Gi", "5%" for eviction signals)."""
    cluster_dns: List[str] = field(default_factory=list)
    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    system_reserved: Dict[str, str] = field(default_factory=dict)
    kube_reserved: Dict[str, str] = field(default_factory=dict)
    eviction_hard: Dict[str, str] = field(default_factory=dict)
    eviction_soft: Dict[str, str] = field(default_factory=dict)
    eviction_soft_grace_period: Dict[str, str] = field(default_factory=dict)
    eviction_max_pod_grace_period: Optional[int] = None
    image_gc_high_threshold_percent: Optional[int] = None
    image_gc_low_threshold_percent: Optional[int] = None
    cpu_cfs_quota: Optional[bool] = None

    def key(self) -> tuple:
        return (tuple(self.cluster_dns), self.max_pods, self.pods_per_core,
                tuple(sorted(self.system_reserved.items())),
                tuple(sorted(self.kube_reserved.items())),
                tuple(sorted(self.eviction_hard.items())),
                tuple(sorted(self.eviction_soft.items())),
                tuple(sorted(self.eviction_soft_grace_period.items())),
                self.eviction_max_pod_grace_period,
                self.image_gc_high_threshold_percent,
                self.image_gc_low_threshold_percent,
                self.cpu_cfs_quota)


@dataclass
class NodeClass:
    """Provider node configuration — the EC2NodeClass analogue
    (pkg/apis/v1/ec2nodeclass.go:29-128). Carries zone/network/boot
    configuration: subnet/security-group/image selector terms, the image
    family, and the node identity role; `ready` gates Create() exactly as
    the reference's readiness condition does
    (pkg/cloudprovider/cloudprovider.go:99-102).
    """
    meta: ObjectMeta
    zones: List[str] = field(default_factory=list)
    capacity_types: List[str] = field(
        default_factory=lambda: [wellknown.CAPACITY_TYPE_ON_DEMAND,
                                 wellknown.CAPACITY_TYPE_SPOT])
    boot_config: Dict[str, str] = field(default_factory=dict)  # userdata analogue
    instance_families: Optional[List[str]] = None  # None = all
    # discovery selectors (None = the cloud's cluster-tagged defaults)
    subnet_selector_terms: Optional[List[SelectorTerm]] = None
    security_group_selector_terms: Optional[List[SelectorTerm]] = None
    image_selector_terms: Optional[List[SelectorTerm]] = None
    image_family: str = "cos"  # AMIFamily analogue (resolver.go:163-180)
    role: str = "default-node-role"
    user_data: str = ""  # appended to the family bootstrap script
    # legacy single-scalar root size, used only when no mapping is given
    block_device_gib: int = 100
    # full spec surface (pkg/apis/v1/ec2nodeclass.go:186-394): device
    # mapping LIST, metadata options, instance-store policy, per-class
    # kubelet config — all drift-hashed and fed into allocatable math
    # (providers/instancetype.py apply_node_class)
    block_device_mappings: Optional[List[BlockDeviceMapping]] = None
    metadata_options: Optional[MetadataOptions] = None
    instance_store_policy: Optional[str] = None  # None | "RAID0"
    kubelet: Optional[KubeletConfiguration] = None
    tags: Dict[str, str] = field(default_factory=dict)
    ready: bool = True
    # status (mirrors EC2NodeClass.status discovered resources,
    # pkg/apis/v1/ec2nodeclass_status.go)
    discovered_zones: List[str] = field(default_factory=list)
    discovered_subnets: List[str] = field(default_factory=list)
    discovered_security_groups: List[str] = field(default_factory=list)
    discovered_images: List[str] = field(default_factory=list)
    instance_profile: str = ""
    status_conditions: Dict[str, bool] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.meta.name

    def root_volume_gib(self) -> int:
        """Root volume size: the mapping flagged root_volume (at most one,
        per the reference's CEL rule), else the first mapping, else the
        legacy scalar."""
        for m in self.block_device_mappings or []:
            if m.root_volume and m.ebs.volume_size_gib:
                return m.ebs.volume_size_gib
        if self.block_device_mappings:
            first = self.block_device_mappings[0]
            if first.ebs.volume_size_gib:
                return first.ebs.volume_size_gib
        return self.block_device_gib

    def static_hash(self) -> str:
        """Drift input — spec-only, status excluded
        (pkg/apis/v1/ec2nodeclass.go:421-427)."""
        payload = json.dumps({
            "zones": sorted(self.zones),
            "capacity_types": sorted(self.capacity_types),
            "boot_config": sorted(self.boot_config.items()),
            "instance_families": sorted(self.instance_families or []),
            "image_family": self.image_family,
            "role": self.role,
            "user_data": self.user_data,
            "block_device_gib": self.block_device_gib,
            "block_device_mappings": [
                m.key() for m in self.block_device_mappings or []],
            "metadata_options": (self.metadata_options.key()
                                 if self.metadata_options else None),
            "instance_store_policy": self.instance_store_policy,
            "kubelet": self.kubelet.key() if self.kubelet else None,
            "tags": sorted(self.tags.items()),
            "subnet_terms": sorted(
                t.key() for t in self.subnet_selector_terms or []),
            "sg_terms": sorted(
                t.key() for t in self.security_group_selector_terms or []),
            "image_terms": sorted(
                t.key() for t in self.image_selector_terms or []),
        }, sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]
