"""Data model: the CRD-shaped objects and the constraint algebra.

Mirrors the API surface of the reference's NodePool / NodeClaim CRDs
(reference: pkg/apis/crds/karpenter.sh_nodepools.yaml,
karpenter.sh_nodeclaims.yaml) and the EC2NodeClass provider CRD
(reference: pkg/apis/v1/ec2nodeclass.go) — re-shaped as plain Python
dataclasses since our control plane is in-process rather than etcd-backed.
"""

from karpenter_tpu.models.resources import (
    Resources,
    parse_quantity,
    format_quantity,
    RESOURCE_AXIS,
)
from karpenter_tpu.models.requirements import Requirement, Requirements, Operator
from karpenter_tpu.models.taints import Taint, Toleration
from karpenter_tpu.models.objects import (
    ObjectMeta,
    Pod,
    Node,
    NodeClaim,
    NodePool,
    BlockDevice,
    BlockDeviceMapping,
    KubeletConfiguration,
    MetadataOptions,
    NodeClass,
    InstanceType,
    Offering,
    TopologySpreadConstraint,
    PodAffinityTerm,
    VolumeClaim,
    Disruption,
    Budget,
)
from karpenter_tpu.models import wellknown

__all__ = [
    "Resources",
    "parse_quantity",
    "format_quantity",
    "RESOURCE_AXIS",
    "Requirement",
    "Requirements",
    "Operator",
    "Taint",
    "Toleration",
    "ObjectMeta",
    "Pod",
    "Node",
    "NodeClaim",
    "NodePool",
    "BlockDevice",
    "BlockDeviceMapping",
    "KubeletConfiguration",
    "MetadataOptions",
    "NodeClass",
    "InstanceType",
    "Offering",
    "TopologySpreadConstraint",
    "PodAffinityTerm",
    "VolumeClaim",
    "Disruption",
    "Budget",
    "wellknown",
]
