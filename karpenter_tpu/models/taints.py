"""Taints and tolerations — standard k8s semantics.

The reference relies on these for NodePool `spec.template.spec.taints` /
`startupTaints` (pkg/apis/crds/karpenter.sh_nodepools.yaml) and the
`karpenter.sh/disruption=disrupting:NoSchedule` disruption taint
(website/content/en/preview/concepts/disruption.md:29-36).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List

NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE

    def __str__(self) -> str:
        return f"{self.key}={self.value}:{self.effect}"


@dataclass(frozen=True)
class Toleration:
    key: str = ""            # "" tolerates every key (operator must be Exists)
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""         # "" tolerates every effect

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key == "":
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


def tolerates_all(taints: Iterable[Taint], tolerations: List[Toleration]) -> bool:
    """True if every hard taint (NoSchedule / NoExecute) is tolerated.
    PreferNoSchedule is soft and never blocks scheduling.
    """
    for taint in taints:
        if taint.effect == PREFER_NO_SCHEDULE:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return False
    return True


def untolerated(taints: Iterable[Taint], tolerations: List[Toleration]) -> List[Taint]:
    return [
        t for t in taints
        if t.effect != PREFER_NO_SCHEDULE
        and not any(tol.tolerates(t) for tol in tolerations)
    ]
