"""Well-known labels, annotations, and taint keys.

Core `karpenter.sh/*` names mirror sigs.k8s.io/karpenter (these names are the
observable API contract — see pkg/apis/crds/*.yaml and
website/content/en/preview/reference/). Provider-scoped names use
`karpenter.tpu/*` where the reference uses `karpenter.k8s.aws/*`
(pkg/apis/v1/labels.go).
"""

# -- core labels ---------------------------------------------------------
NODEPOOL_LABEL = "karpenter.sh/nodepool"
CAPACITY_TYPE_LABEL = "karpenter.sh/capacity-type"
INITIALIZED_LABEL = "karpenter.sh/initialized"
REGISTERED_LABEL = "karpenter.sh/registered"

CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"

# -- kubernetes well-known labels ---------------------------------------
ARCH_LABEL = "kubernetes.io/arch"
OS_LABEL = "kubernetes.io/os"
HOSTNAME_LABEL = "kubernetes.io/hostname"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
ZONE_LABEL = "topology.kubernetes.io/zone"
REGION_LABEL = "topology.kubernetes.io/region"

ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
OS_LINUX = "linux"

# -- provider labels (reference: karpenter.k8s.aws/* — pkg/apis/v1/labels.go)
INSTANCE_CATEGORY_LABEL = "karpenter.tpu/instance-category"
INSTANCE_FAMILY_LABEL = "karpenter.tpu/instance-family"
INSTANCE_GENERATION_LABEL = "karpenter.tpu/instance-generation"
INSTANCE_SIZE_LABEL = "karpenter.tpu/instance-size"
INSTANCE_CPU_LABEL = "karpenter.tpu/instance-cpu"
INSTANCE_MEMORY_LABEL = "karpenter.tpu/instance-memory"  # MiB
INSTANCE_GPU_COUNT_LABEL = "karpenter.tpu/instance-gpu-count"
INSTANCE_GPU_NAME_LABEL = "karpenter.tpu/instance-gpu-name"
INSTANCE_NETWORK_BANDWIDTH_LABEL = "karpenter.tpu/instance-network-bandwidth"
INSTANCE_LOCAL_NVME_LABEL = "karpenter.tpu/instance-local-nvme"
NODECLASS_LABEL = "karpenter.tpu/nodeclass"

# -- taints --------------------------------------------------------------
DISRUPTED_TAINT_KEY = "karpenter.sh/disrupted"
DISRUPTION_TAINT_KEY = "karpenter.sh/disruption"   # value "disrupting"
UNREGISTERED_TAINT_KEY = "karpenter.sh/unregistered"

# -- annotations ---------------------------------------------------------
DO_NOT_DISRUPT_ANNOTATION = "karpenter.sh/do-not-disrupt"
POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"
NODEPOOL_HASH_ANNOTATION = "karpenter.sh/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION = "karpenter.sh/nodepool-hash-version"
NODECLASS_HASH_ANNOTATION = "karpenter.tpu/nodeclass-hash"
NODECLASS_HASH_VERSION_ANNOTATION = "karpenter.tpu/nodeclass-hash-version"
# gang scheduling (ISSUE 15): tightly-coupled multi-host workloads
# declare all-or-nothing, rank-adjacent placement via pod annotations.
# gang-name groups the members, gang-size declares the expected member
# count (a gang with fewer pending members than declared is incomplete
# and strands whole), gang-topology names the adjacency domain the
# members must share: "slice" (the zone axis — a TPU multi-host slice),
# "rack" (the capacity-type axis doubling as the rack domain when the
# catalog encodes racks that way), or "none" (atomic, no adjacency).
GANG_NAME_ANNOTATION = "karpenter.tpu/gang-name"
GANG_SIZE_ANNOTATION = "karpenter.tpu/gang-size"
GANG_TOPOLOGY_ANNOTATION = "karpenter.tpu/gang-topology-domain"
# priority & preemption (ISSUE 16): an integer priority override that
# outranks both priorityClassName and the spec `priority` field —
# scheduling packs strict priority bands high-to-low, and the
# preemption planner may evict strictly-lower-priority pods to seat a
# stranded higher-priority one.  Parsed by scheduling.types.priority_of
# (the ONE grammar owner); malformed values degrade to the next source.
PRIORITY_ANNOTATION = "karpenter.tpu/priority"
# stamped on planned preemption victims by the provisioner (value: the
# plan id); the preemption controller drains annotated victims
# atomically per plan through the termination-style eviction path
PREEMPT_PLAN_ANNOTATION = "karpenter.tpu/preempt-plan"
PREEMPT_FOR_ANNOTATION = "karpenter.tpu/preempted-for"

# -- finalizers ----------------------------------------------------------
TERMINATION_FINALIZER = "karpenter.sh/termination"
NODECLASS_TERMINATION_FINALIZER = "karpenter.tpu/termination"

# Labels the scheduler knows how to derive from instance types / offerings,
# so a pod/NodePool may require them even when a template doesn't list them
# (reference: scheduling.WellKnownLabels allowUndefined behavior).
WELL_KNOWN_LABELS = frozenset({
    NODEPOOL_LABEL,
    CAPACITY_TYPE_LABEL,
    ARCH_LABEL,
    OS_LABEL,
    HOSTNAME_LABEL,
    INSTANCE_TYPE_LABEL,
    ZONE_LABEL,
    REGION_LABEL,
    INSTANCE_CATEGORY_LABEL,
    INSTANCE_FAMILY_LABEL,
    INSTANCE_GENERATION_LABEL,
    INSTANCE_SIZE_LABEL,
    INSTANCE_CPU_LABEL,
    INSTANCE_MEMORY_LABEL,
    INSTANCE_GPU_COUNT_LABEL,
    INSTANCE_GPU_NAME_LABEL,
    INSTANCE_NETWORK_BANDWIDTH_LABEL,
    INSTANCE_LOCAL_NVME_LABEL,
    NODECLASS_LABEL,
})

# Restricted: users may not set these directly on NodePool templates.
RESTRICTED_LABELS = frozenset({
    HOSTNAME_LABEL,
})
