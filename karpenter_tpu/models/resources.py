"""Resource quantities and the canonical resource axis.

The reference models node capacity/allocatable as k8s `v1.ResourceList`
(reference: pkg/providers/instancetype/types.go:193-210 builds cpu, memory,
ephemeral-storage, pods, and extended resources like nvidia.com/gpu).

For the TPU solver every resource must live on a fixed tensor axis, so we
define a canonical ordering (`RESOURCE_AXIS`) covering the resources the
reference computes, plus a small number of extended-resource slots that are
interned on demand. Quantities are held as floats in solver-friendly units:

  cpu               millicores
  memory            MiB   (keeps f32-exact at TPU precision for TB-range nodes)
  ephemeral-storage MiB
  pods              count
  accelerators      count

Parsing follows k8s quantity syntax ("100m", "1.5Gi", "2T", plain ints).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping

# Canonical dense axis. Extended resources beyond these are interned into
# EXTENDED slots (the reference similarly special-cases gpu/neuron/efa —
# pkg/providers/instancetype/types.go:193-210).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL = "ephemeral-storage"
PODS = "pods"
GPU = "gpu"  # generic accelerator slot (nvidia.com/gpu et al. map here)
# attachable persistent-volume slots: the reference enforces per-node
# volume attach limits during scheduling (scheduling.md:381-417 /
# instance-store policy ec2nodeclass.go:384-394); modeling them as a
# resource axis rides the same pods×types capacity tensors as cpu/memory
VOLUMES = "volumes"

RESOURCE_AXIS: tuple[str, ...] = (CPU, MEMORY, EPHEMERAL, PODS, GPU, VOLUMES)
AXIS_INDEX: dict[str, int] = {name: i for i, name in enumerate(RESOURCE_AXIS)}

# Names that alias onto the canonical axis.
_ALIASES = {
    "nvidia.com/gpu": GPU,
    "amd.com/gpu": GPU,
    "google.com/tpu": GPU,
    "aws.amazon.com/neuron": GPU,
}

_SUFFIX = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}

_QTY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)$")


def parse_quantity(value: "str | int | float") -> float:
    """Parse a k8s quantity into a raw float (bytes / cores / count)."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QTY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    num, suffix = m.groups()
    base = float(num)
    if suffix == "":
        return base
    if suffix == "m":
        return base / 1000.0
    if suffix in _SUFFIX:
        return base * _SUFFIX[suffix]
    raise ValueError(f"invalid quantity suffix {suffix!r} in {value!r}")


def format_quantity(value: float) -> str:
    if value == int(value):
        return str(int(value))
    return f"{value:g}"


def _to_solver_units(name: str, raw: float) -> float:
    """raw (cores / bytes / count) → solver units (millicores / MiB / count)."""
    if name == CPU:
        return raw * 1000.0
    if name in (MEMORY, EPHEMERAL):
        return raw / 2**20
    return raw


def _from_solver_units(name: str, val: float) -> float:
    if name == CPU:
        return val / 1000.0
    if name in (MEMORY, EPHEMERAL):
        return val * 2**20
    return val


class Resources:
    """A dense resource vector over RESOURCE_AXIS, in solver units.

    Arithmetic mirrors the reference's resources helpers
    (sigs.k8s.io/karpenter/pkg/utils/resources: Merge, Subtract, Fits).
    """

    __slots__ = ("v", "_cached_key")

    def __init__(self, v: "list[float] | None" = None):
        self.v = list(v) if v is not None else [0.0] * len(RESOURCE_AXIS)
        self._cached_key = None

    # -- constructors ----------------------------------------------------
    @classmethod
    def parse(cls, spec: Mapping[str, "str | int | float"]) -> "Resources":
        """From a k8s-style resource map, e.g. {"cpu": "100m", "memory": "1Gi"}."""
        r = cls()
        for name, q in spec.items():
            canon = _ALIASES.get(name, name)
            if canon not in AXIS_INDEX:
                raise ValueError(f"unknown resource {name!r}")
            r.v[AXIS_INDEX[canon]] += _to_solver_units(canon, parse_quantity(q))
        return r

    @classmethod
    def limits(cls, spec: "Mapping[str, str | int | float] | None" = None,
               **kw: float) -> "Resources":
        """A limits vector: axes not named are unconstrained (+inf), so a
        cpu-only NodePool limit doesn't implicitly zero out memory
        (reference: NodePool.spec.limits constrains only listed resources).
        Named axes may be zero to forbid a resource entirely.
        """
        r = cls([float("inf")] * len(RESOURCE_AXIS))
        if spec:
            for name, q in spec.items():
                canon = _ALIASES.get(name, name)
                r.v[AXIS_INDEX[canon]] = _to_solver_units(canon, parse_quantity(q))
        for name, val in kw.items():
            r.v[AXIS_INDEX[name.replace("_", "-")]] = float(val)
        return r

    @classmethod
    def of(cls, **kw: float) -> "Resources":
        """From solver units directly: Resources.of(cpu=2000, memory=4096)."""
        r = cls()
        for name, val in kw.items():
            name = name.replace("_", "-")
            r.v[AXIS_INDEX[name]] = float(val)
        return r

    def copy(self) -> "Resources":
        return Resources(self.v)

    # -- arithmetic ------------------------------------------------------
    def __add__(self, other: "Resources") -> "Resources":
        return Resources([a + b for a, b in zip(self.v, other.v)])

    def __sub__(self, other: "Resources") -> "Resources":
        return Resources([a - b for a, b in zip(self.v, other.v)])

    def __iadd__(self, other: "Resources") -> "Resources":
        for i, b in enumerate(other.v):
            self.v[i] += b
        self._cached_key = None
        return self

    def __mul__(self, k: float) -> "Resources":
        return Resources([a * k for a in self.v])

    def fits(self, capacity: "Resources", eps: float = 1e-9) -> bool:
        """True if self ≤ capacity elementwise (with float slack). Plain
        indexed loop: this is the oracle's innermost check (~1M calls per
        5k-pod solve) and the generator+zip form cost ~2x."""
        a, b = self.v, capacity.v
        for i in range(len(a)):
            if a[i] > b[i] + eps:
                return False
        return True

    def any_negative(self) -> bool:
        return any(a < -1e-9 for a in self.v)

    def is_zero(self) -> bool:
        return all(abs(a) < 1e-9 for a in self.v)

    # -- accessors -------------------------------------------------------
    def get(self, name: str) -> float:
        return self.v[AXIS_INDEX[_ALIASES.get(name, name)]]

    def set(self, name: str, val: float) -> None:
        self.v[AXIS_INDEX[_ALIASES.get(name, name)]] = float(val)
        self._cached_key = None

    @property
    def cpu(self) -> float:
        return self.v[AXIS_INDEX[CPU]]

    @property
    def memory(self) -> float:
        return self.v[AXIS_INDEX[MEMORY]]

    @property
    def pods(self) -> float:
        return self.v[AXIS_INDEX[PODS]]

    def to_dict(self) -> Dict[str, float]:
        """Back to k8s-style raw units (cores / bytes / count)."""
        return {
            name: _from_solver_units(name, val)
            for name, val in zip(RESOURCE_AXIS, self.v)
            if val != 0.0
        }

    def to_dict_solver(self) -> Dict[str, float]:
        """Solver units as-is (millicores / MiB / count) — the catalog
        table's lossless serialization (providers/catalog.py dump_catalog)."""
        return {name: val for name, val in zip(RESOURCE_AXIS, self.v)
                if val != 0.0}

    # magnitude used for FFD descending sort (reference sorts pods by
    # resource size — designs/bin-packing.md:28-29; core uses cpu then mem).
    def sort_key(self) -> tuple[float, float]:
        return (self.cpu, self.memory)

    # eq/hash quantize to 1e-6 solver units so the pair is consistent
    # (Resources participates in Pod.scheduling_key equivalence classes).
    # Cached: grouping 50k pods hashes/compares these in the hot path; every
    # mutating method below invalidates.
    def _key(self) -> tuple:
        if self._cached_key is None:
            self._cached_key = tuple(round(a, 6) for a in self.v)
        return self._cached_key

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Resources) and self._key() == other._key()

    def __hash__(self) -> int:
        return hash(self._key())

    def __repr__(self) -> str:
        parts = ", ".join(
            f"{n}={v:g}" for n, v in zip(RESOURCE_AXIS, self.v) if v
        )
        return f"Resources({parts})"


def merge(items: Iterable[Resources]) -> Resources:
    out = Resources()
    for it in items:
        out += it
    return out
