"""v1beta1 compatibility API + conversion.

The reference ships a v1beta1 API family and a conversion webhook so
pre-v1 manifests keep working during migration
(/root/reference/pkg/apis/v1beta1, pkg/webhooks/webhooks.go:1-57,
pkg/apis/v1/ec2nodeclass_conversion.go). Our control plane is
in-process, so the conversion seam is at object ADMISSION instead of an
apiserver webhook: `admit()` accepts either API version and hands the
stores v1 objects.

The shape differences mirrored here are the reference's real v1beta1→v1
moves:

- NodePool: `expireAfter` lived under spec.disruption in v1beta1 and
  moved to the node template in v1; consolidationPolicy
  `WhenUnderutilized` was renamed `WhenEmptyOrUnderutilized`.
- Kubelet configuration lived on the NodePool's node TEMPLATE in
  v1beta1 and moved to the provider NodeClass in v1 (the reference
  carries it across via a compatibility annotation during conversion).
- NodeClass: selector terms were `amiSelectorTerms`/`amiFamily`
  spellings (image* in v1), and metadata options defaulted to optional
  tokens (required in v1).

Round-tripping is lossless for everything expressible in both versions;
`to_v1`/`from_v1` are inverses on that subset (tests/test_v1beta1.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.models.objects import (
    CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED,
    CONSOLIDATE_WHEN_UNDERUTILIZED,
    Budget,
    Disruption,
    KubeletConfiguration,
    MetadataOptions,
    NodeClass,
    NodePool,
    ObjectMeta,
    SelectorTerm,
    Taint,
)
from karpenter_tpu.models.requirements import Requirements
from karpenter_tpu.models.resources import Resources

# annotation carrying a v1beta1 pool-level kubelet config through v1
# objects (role of the reference's
# compatibility.karpenter.k8s.aws/v1beta1-kubelet-conversion annotation)
KUBELET_COMPAT_ANNOTATION = "compatibility.karpenter.tpu/v1beta1-kubelet"


@dataclass
class V1Beta1Disruption:
    consolidation_policy: str = CONSOLIDATE_WHEN_UNDERUTILIZED
    consolidate_after: float = 0.0
    expire_after: Optional[float] = None  # v1beta1: lives HERE
    budgets: List[Budget] = field(default_factory=lambda: [Budget(nodes="10%")])


@dataclass
class V1Beta1NodePool:
    """The old NodePool shape: expireAfter under disruption, kubelet on
    the node template."""
    meta: ObjectMeta
    node_class_ref: str = "default"
    requirements: Requirements = field(default_factory=Requirements)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    kubelet: Optional[KubeletConfiguration] = None  # template-level in v1beta1
    disruption: V1Beta1Disruption = field(default_factory=V1Beta1Disruption)
    limits: Optional["Resources"] = None
    weight: int = 0


@dataclass
class V1Beta1NodeClass:
    """The old NodeClass shape: ami* spellings, optional metadata tokens."""
    meta: ObjectMeta
    ami_family: str = "cos"
    ami_selector_terms: Optional[List[SelectorTerm]] = None
    subnet_selector_terms: Optional[List[SelectorTerm]] = None
    security_group_selector_terms: Optional[List[SelectorTerm]] = None
    role: str = "default-node-role"
    user_data: str = ""
    block_device_gib: int = 100
    metadata_http_tokens: str = "optional"  # v1 default: required
    tags: Dict[str, str] = field(default_factory=dict)


def _policy_to_v1(policy: str) -> str:
    if policy == CONSOLIDATE_WHEN_UNDERUTILIZED:
        return CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED
    return policy


def _policy_from_v1(policy: str) -> str:
    if policy == CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED:
        return CONSOLIDATE_WHEN_UNDERUTILIZED
    return policy


def _dump_kubelet(k: KubeletConfiguration) -> str:
    import dataclasses
    import json
    return json.dumps(dataclasses.asdict(k), sort_keys=True)


def _load_kubelet(raw: str) -> Optional[KubeletConfiguration]:
    import json
    try:
        return KubeletConfiguration(**json.loads(raw))
    except (ValueError, TypeError):
        return None


def nodepool_to_v1(b: V1Beta1NodePool) -> NodePool:
    """expireAfter moves disruption→template; the template kubelet rides
    a compatibility annotation as NAMED fields (parsed back by
    nodepool_from_v1 and by the NodeClass attach at admission — the
    round trip is lossless). Object-metadata annotations and template
    annotations stay separate dicts; the compat key lives on the object
    metadata, like the reference's conversion annotation."""
    meta_ann = dict(b.meta.annotations)
    if b.kubelet is not None:
        meta_ann[KUBELET_COMPAT_ANNOTATION] = _dump_kubelet(b.kubelet)
    return NodePool(
        meta=replace(b.meta, annotations=meta_ann),
        node_class_ref=b.node_class_ref,
        requirements=b.requirements,
        taints=list(b.taints),
        startup_taints=list(b.startup_taints),
        labels=dict(b.labels),
        annotations=dict(b.annotations),
        expire_after=b.disruption.expire_after,
        disruption=Disruption(
            consolidation_policy=_policy_to_v1(
                b.disruption.consolidation_policy),
            consolidate_after=b.disruption.consolidate_after,
            budgets=list(b.disruption.budgets)),
        limits=b.limits,
        weight=b.weight,
    )


def nodepool_from_v1(p: NodePool,
                     kubelet: Optional[KubeletConfiguration] = None,
                     ) -> V1Beta1NodePool:
    if kubelet is None:
        raw = p.meta.annotations.get(KUBELET_COMPAT_ANNOTATION)
        if raw is not None:
            kubelet = _load_kubelet(raw)
    meta_ann = {k: v for k, v in p.meta.annotations.items()
                if k != KUBELET_COMPAT_ANNOTATION}
    return V1Beta1NodePool(
        meta=replace(p.meta, annotations=meta_ann),
        node_class_ref=p.node_class_ref,
        requirements=p.requirements,
        taints=list(p.taints),
        startup_taints=list(p.startup_taints),
        labels=dict(p.labels),
        annotations=dict(p.annotations),
        kubelet=kubelet,
        disruption=V1Beta1Disruption(
            consolidation_policy=_policy_from_v1(
                p.disruption.consolidation_policy),
            consolidate_after=p.disruption.consolidate_after,
            expire_after=p.expire_after,
            budgets=list(p.disruption.budgets)),
        limits=p.limits,
        weight=p.weight,
    )


def nodeclass_to_v1(b: V1Beta1NodeClass,
                    kubelet: Optional[KubeletConfiguration] = None,
                    ) -> NodeClass:
    """ami* → image*; optional metadata tokens survive explicitly (the
    v1 default hardened to required, so conversion must pin the old
    behavior rather than silently change launches)."""
    return NodeClass(
        meta=b.meta,
        image_family=b.ami_family,
        image_selector_terms=b.ami_selector_terms,
        subnet_selector_terms=b.subnet_selector_terms,
        security_group_selector_terms=b.security_group_selector_terms,
        role=b.role,
        user_data=b.user_data,
        block_device_gib=b.block_device_gib,
        metadata_options=MetadataOptions(http_tokens=b.metadata_http_tokens),
        kubelet=kubelet,
        tags=dict(b.tags),
    )


def nodeclass_from_v1(nc: NodeClass) -> V1Beta1NodeClass:
    return V1Beta1NodeClass(
        meta=nc.meta,
        ami_family=nc.image_family,
        ami_selector_terms=nc.image_selector_terms,
        subnet_selector_terms=nc.subnet_selector_terms,
        security_group_selector_terms=nc.security_group_selector_terms,
        role=nc.role,
        user_data=nc.user_data,
        block_device_gib=nc.block_device_gib,
        metadata_http_tokens=(nc.metadata_options.http_tokens
                              if nc.metadata_options else "required"),
        tags=dict(nc.tags),
    )


def _attach_pending_kubelet(cluster, nc: NodeClass) -> None:
    """Apply any admitted pool's compat-annotation kubelet to this class
    — admission order (pool-then-class or class-then-pool) must not
    matter, exactly as kubectl-apply ordering doesn't. An explicit v1
    kubelet on the class wins over the converted template config.

    LIMITATION (intentional, observable): v1 hangs kubelet config on the
    NodeClass, so several v1beta1 pools sharing one class flatten to ONE
    config — the first attached wins, and any DIFFERING later config
    raises a `KubeletConversionConflict` event telling the operator to
    split the class (the reference's v1 migration guide gives the same
    instruction for per-pool kubelet divergence)."""
    pending = []
    for pool in cluster.nodepools.list():
        if pool.node_class_ref != nc.name:
            continue
        raw = pool.meta.annotations.get(KUBELET_COMPAT_ANNOTATION)
        if raw is None:
            continue
        kub = _load_kubelet(raw)
        if kub is not None:
            pending.append((pool.name, kub))
    for pool_name, kub in pending:
        if nc.kubelet is None:
            nc.kubelet = kub
            cluster.nodeclasses.update(nc)
        elif nc.kubelet != kub:
            cluster.record_event(
                "NodeClass", nc.name, "KubeletConversionConflict",
                f"pool {pool_name}'s v1beta1 kubelet config differs from "
                f"the one already on this class; split the NodeClass to "
                f"keep per-pool kubelet settings")


def admit(cluster, obj) -> object:
    """The conversion-webhook seam for an in-process store: accepts
    either API version, converts v1beta1 to v1, and creates the v1
    object(s). A v1beta1 pool's template kubelet is applied to its
    referenced NodeClass regardless of which object is admitted first
    (the reference's conversion carries it the same direction)."""
    if isinstance(obj, V1Beta1NodePool):
        obj = nodepool_to_v1(obj)
        # falls through to the NodePool branch: a converted pool and a
        # pre-converted v1 pool carrying the compat annotation behave
        # identically regardless of admission order
    if isinstance(obj, V1Beta1NodeClass):
        nc = cluster.nodeclasses.create(nodeclass_to_v1(obj))
        _attach_pending_kubelet(cluster, nc)
        return nc
    if isinstance(obj, NodePool):
        out = cluster.nodepools.create(obj)
        if KUBELET_COMPAT_ANNOTATION in obj.meta.annotations:
            nc = cluster.nodeclasses.get(obj.node_class_ref)
            if nc is not None:
                _attach_pending_kubelet(cluster, nc)
        return out
    if isinstance(obj, NodeClass):
        nc = cluster.nodeclasses.create(obj)
        _attach_pending_kubelet(cluster, nc)
        return nc
    raise TypeError(f"unadmittable object {type(obj).__name__}")
