"""Provider refresh controllers — singleton polling loops.

Mirrors pkg/controllers/providers: the instance-type controller re-pulls
instance types/offerings on an interval
(providers/instancetype/controller.go:68) and the pricing controller
refreshes the price books (providers/pricing/controller.go:67), feeding the
respective provider caches so the scheduling hot path never blocks on a
cloud API.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.clock import Clock, RealClock
from karpenter_tpu.utils.logging import get_logger

DEFAULT_REFRESH_INTERVAL = 300.0  # instance-type cache TTL class (cache.go)


class _IntervalController:
    interval = DEFAULT_REFRESH_INTERVAL

    def __init__(self, clock: Optional[Clock] = None,
                 interval: Optional[float] = None):
        self.clock = clock or RealClock()
        if interval is not None:
            self.interval = interval
        self._last: Optional[float] = None

    def reconcile(self) -> None:
        now = self.clock.now()
        if self._last is not None and now - self._last < self.interval:
            return
        self._last = now
        self.refresh()

    def refresh(self) -> None:
        raise NotImplementedError


class PricingRefresh(_IntervalController):
    name = "pricing-refresh"

    def __init__(self, pricing, clock=None, interval=None):
        super().__init__(clock, interval)
        self.pricing = pricing

    def refresh(self) -> None:
        try:
            self.pricing.update()
            # the timeline's price.refresh capture point: a successful
            # book refresh is a cluster-trajectory input (solves after
            # it rank against new prices)
            from karpenter_tpu.timeline import events as tev
            from karpenter_tpu.timeline import recorder as trec
            trec.emit(tev.PRICE_REFRESH, name=self.name)
        except Exception as e:  # noqa: BLE001 — keep the stale book (static
            # fallback semantics, pricing.go:54-59) — but visibly: a price
            # book aging silently is how cost regressions go unnoticed
            # (kt-lint exception-hygiene)
            get_logger(self.name).warn(
                "pricing update failed; keeping the stale book",
                error=str(e)[:200])
            metrics.RECONCILE_ERRORS.inc(controller=self.name)


class InstanceTypeRefresh(_IntervalController):
    name = "instancetype-refresh"

    def __init__(self, instance_types, clock=None, interval=None):
        super().__init__(clock, interval)
        self.instance_types = instance_types

    def refresh(self) -> None:
        # reading seqnum sweeps expired ICE entries (their disappearance
        # must invalidate downstream cache keys), then drop cached lists so
        # the next scheduler call re-pulls the catalog (which logs the
        # discovered count, change-gated, on its own fetch)
        _ = self.instance_types.unavailable.seqnum
        self.instance_types.invalidate()
