"""Cluster-state → ScheduleInput assembly, shared by the provisioner and
the disruption simulator (SURVEY §2.2 Cluster state: one in-memory model
feeds both hot paths).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.models.objects import InstanceType, NodePool, Offering, Pod
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.models.taints import tolerates_all
from karpenter_tpu.scheduling import ExistingNode, ScheduleInput
from karpenter_tpu.scheduling.types import effective_request


class SolveCacheFeed:
    """Cluster-event half of the solver's delta SolveCache
    (solver/delta.py): subscribes a cluster watch and drains store
    mutations into (dirty pod names, dirty node names) for
    ``TPUSolver.delta_invalidate`` — only the touched groups/nodes are
    invalidated, so a steady-state pass stays O(churn).  Node-shaped
    events (nodes, nodeclaims) invalidate cached node rows; pod events
    mark their groups changed.  A ChangeMonitor gates the invalidation
    log line so a churn-heavy cluster doesn't spam per-pass."""

    _NODE_KINDS = ("nodes", "nodeclaims")

    def __init__(self, cluster: Cluster):
        from karpenter_tpu.utils.logging import ChangeMonitor
        self._cluster = cluster
        self._watch = cluster.watch()
        self._monitor = ChangeMonitor()

    def drain(self):
        """(dirty pod names, dirty node names, flood).  The cluster
        Watch's bounded buffer drops OLD events on overflow — harmless
        for its level-driven consumers, but THIS consumer is
        edge-driven: a dropped node event is a lost invalidation.  A
        full drain therefore reports flood=True and the cache degrades
        to all-dirty (one counted fallback), never a silent miss."""
        pods, nodes, flood, _claims = self._drain_kinds()
        return set(pods), set(nodes), flood

    def _drain_kinds(self):
        """drain() plus the nodeclaim-kind subset of the node names —
        the incremental index (ISSUE 20) absorbs claim events
        separately (a claim without a registered node row changes no
        cached row), while the walk path keeps treating them as node
        dirt."""
        events = self._watch.drain()
        # dicts, not sets: the index's member-order contract needs pod
        # names in FIRST-occurrence event order (== store-append order
        # for creations); the walk path only reads them as sets
        pods: dict = {}
        nodes: dict = {}
        claims: set = set()
        for ev in events:
            if ev.kind == "pods":
                pods.setdefault(ev.name, None)
            elif ev.kind in self._NODE_KINDS:
                nodes.setdefault(ev.name, None)
                if ev.kind == "nodeclaims":
                    claims.add(ev.name)
        flood = len(events) >= (self._watch._buffer.maxlen or 0)
        return pods, nodes, flood, claims

    def feed(self, solver) -> None:
        """Drain and forward to a solver that supports the delta seam
        (the in-process TPUSolver; the remote client's daemon runs its
        own value-based diff and needs no feed).  Each dirty name is
        resolved to its CURRENT object (None = deleted) so the
        solver's incremental index can absorb the event at feed time —
        an O(churn) store probe here replaces an O(cluster) walk per
        solve pass.  Claim-kind names resolve through the node store
        too (a registered claim shares its node's name; an unregistered
        one resolves to None and only dirties the index if a cached
        row bears the name)."""
        pods, nodes, flood, claims = self._drain_kinds()
        if not pods and not nodes and not flood:
            return
        inval = getattr(solver, "delta_invalidate", None)
        if inval is None:
            return
        cl = self._cluster
        # resolved in event order (pod_objs' insertion order carries
        # the store-append order the index's member contract needs)
        pod_objs = {n: cl.pods.get(n) for n in pods}
        node_objs = {n: cl.nodes.get(n)
                     for n in nodes if n not in claims}
        try:
            inval(pods=set(pods), nodes=set(nodes), flood=flood,
                  pod_objs=pod_objs, node_objs=node_objs,
                  claims=tuple(claims))
        except TypeError:
            # an older solver seam (remote daemon shim, test double)
            # that predates the object-bearing feed: name sets carry
            # all the walk path needs
            inval(pods=set(pods), nodes=set(nodes), flood=flood)
        from karpenter_tpu.utils.logging import get_logger
        if self._monitor.has_changed(
                "delta-invalidate", (len(pods), len(nodes), flood)):
            get_logger("solver").debug(
                "delta cache invalidation", pods=len(pods),
                nodes=len(nodes), flood=flood)


class GatedSolver:
    """The TPU solver behind its feature gate with the CPU oracle as
    fallback — shared by the provisioner and the disruption simulator so
    they share one device catalog cache (solver down ⇒ fall back, never
    fail — SURVEY §5)."""

    def __init__(self, options, cluster: Cluster):
        self.options = options
        self.cluster = cluster
        # lazily-built in-process solver used as DEGRADED MODE when the
        # remote service is down/breaker-open (ISSUE 7): better than the
        # oracle, never constructed while the service is healthy. The
        # lock guards the lazy init — the provisioner and the disruption
        # simulator share this GatedSolver and can hit the degraded path
        # concurrently, and a TPUSolver construction is too expensive to
        # duplicate.
        self._remote = bool(options.solver_endpoint)
        self._local = None
        import threading
        self._local_init_lock = threading.Lock()
        if options.solver_endpoint:
            # remote TPU-owning solver process (native/solverd.cc): same
            # solve/solve_batch seam, coalesced in the daemon's window.
            # The client carries the shared availability layer — bounded
            # retries with backoff, per-request deadlines shipped in the
            # frame, and the circuit breaker whose open state is what
            # "degraded mode" means operationally.
            from karpenter_tpu.service import (
                CircuitBreaker,
                RetryPolicy,
                SolverServiceClient,
            )
            timeout = getattr(options, "service_request_timeout", 60.0)
            self.tpu = SolverServiceClient(
                options.solver_endpoint,
                timeout=timeout,
                retry=RetryPolicy(
                    attempts=getattr(options, "service_retry_attempts", 3),
                    deadline=timeout),
                breaker=CircuitBreaker(
                    threshold=getattr(options,
                                      "service_breaker_threshold", 5),
                    cooldown=getattr(options,
                                     "service_breaker_cooldown", 10.0)),
                # multi-tenant fleet identity (ISSUE 11): one cluster =
                # one tenant by default, so a shared solverd queues this
                # control plane fairly against its peer clusters
                tenant=getattr(options, "service_tenant", None)
                or getattr(options, "cluster_name", None),
                priority=getattr(options, "service_priority", 0))
        else:
            from karpenter_tpu.solver import TPUSolver
            # SOLVER_MESH (options) configures the mesh story;
            # KARPENTER_TPU_MESH is the operator's rollback knob and
            # overrides inside _resolve_mesh — flipping it to "off" on a
            # misbehaving deployment restores the single-device path
            # without an image or options change
            # SOLVER_DELTA configures the incremental delta-solve story
            # the same way; KARPENTER_TPU_DELTA is its rollback knob,
            # resolved inside the solver
            self.tpu = TPUSolver(
                max_nodes=options.solver_max_nodes,
                mesh=getattr(options, "solver_mesh", "auto"),
                delta=getattr(options, "solver_delta", "auto"),
                incr=getattr(options, "solver_incr", "auto"))
            # event-driven delta-cache invalidation: cluster watch →
            # dirty pod/node names → TPUSolver.delta_invalidate
            self._delta_feed = SolveCacheFeed(cluster)
            # the feed delivers OBJECTS with every event from here on,
            # so the solver's "auto" incremental index may trust the
            # stream (ISSUE 20) — arming stays strictly tied to the
            # feed's existence; the remote/degraded solvers never arm
            self.tpu.incr_arm()
            # warm the native host-ops build at startup, never inside a
            # latency-sensitive solve
            from karpenter_tpu.native import hostops
            hostops()

    # largest pod batch one ORACLE pass will chew through when the device
    # path is down: at ~2.4k pods/s of oracle throughput this caps a
    # degraded provisioning pass near ~3 s instead of the 20 s cliff the
    # 50k headline would cost (VERDICT r3 weak #6). Shed pods stay
    # PENDING — the provisioner re-batches them next pass, so a TPU
    # outage degrades to bounded-latency incremental progress, never a
    # stalled loop or spurious unschedulable verdicts.
    ORACLE_SHED_LIMIT = 8000

    def _local_solver(self):
        """The degraded-mode in-process solver behind the remote client.
        None when this GatedSolver IS the in-process solver (nothing to
        degrade to but the oracle) or the fallback is disabled."""
        if not self._remote or not getattr(
                self.options, "service_local_fallback", True):
            return None
        if self._local is None:
            with self._local_init_lock:
                if self._local is None:
                    from karpenter_tpu.solver import TPUSolver
                    self._local = TPUSolver(
                        max_nodes=self.options.solver_max_nodes,
                        mesh=getattr(self.options, "solver_mesh", "auto"),
                        delta=getattr(self.options, "solver_delta",
                                      "auto"))
        return self._local

    def _degraded_solve(self, inp: ScheduleInput, source: str,
                        max_nodes: Optional[int]):
        """One in-process solve while the service is unavailable.
        Returns None to fall through to the oracle."""
        local = self._local_solver()
        if local is None:
            return None
        from karpenter_tpu.solver import UnsupportedPods
        from karpenter_tpu.utils import tracing
        try:
            with tracing.span("solver.degraded_local", source=source,
                              pods=len(inp.pods)):
                return local.solve(inp, max_nodes=max_nodes)
        except UnsupportedPods:
            return None
        except Exception as e:  # noqa: BLE001
            from karpenter_tpu.utils.logging import get_logger
            get_logger("solver").warn(
                "degraded-mode local solve failed; falling back to oracle",
                source=source, error=str(e)[:200])
            return None

    def solve(self, inp: ScheduleInput, source: str = "solver",
              max_nodes: Optional[int] = None):
        from karpenter_tpu.scheduling import Scheduler
        from karpenter_tpu.solver import UnsupportedPods
        from karpenter_tpu.utils import metrics, tracing
        if self.options.feature_gates.tpu_solver:
            feed = getattr(self, "_delta_feed", None)
            if feed is not None:
                feed.feed(self.tpu)
            try:
                return self.tpu.solve(inp, max_nodes=max_nodes)
            except UnsupportedPods:
                pass  # constraints the encoder can't express yet → oracle
            except Exception as e:  # noqa: BLE001
                from karpenter_tpu.utils.logging import get_logger
                get_logger("solver").warn(
                    "device solve failed; entering degraded mode",
                    source=source, error=str(e)[:200])
                self.cluster.record_event(
                    "Provisioner", source, "SolverFallback", str(e))
                res = self._degraded_solve(inp, source, max_nodes)
                if res is not None:
                    return res
        metrics.SOLVER_SOLVES.inc(path="oracle")
        # load shedding is only sound for PROVISIONING (unsolved pods stay
        # pending and retry): a disruption simulation must judge its whole
        # pod set or its feasible/infeasible verdict is meaningless
        if (source == "provisioning"
                and len(inp.pods) > self.ORACLE_SHED_LIMIT):
            import dataclasses
            shed = len(inp.pods) - self.ORACLE_SHED_LIMIT
            metrics.SOLVER_SHED_PODS.inc(shed)
            self.cluster.record_event(
                "Provisioner", source, "SolverLoadShed",
                f"oracle fallback: deferring {shed} pods to the next pass")
            inp = dataclasses.replace(
                inp, pods=inp.pods[:self.ORACLE_SHED_LIMIT])
        with tracing.span("solver.oracle", pods=len(inp.pods),
                          source=source):
            return Scheduler(inp).solve()

    def warmup(self, inp: ScheduleInput, shapes=()) -> int:
        """Padding-bucket precompile at operator startup (never on the
        solve path): delegates to the in-process solver's warmup() or the
        solverd client's remote variant.  Best-effort — a warm-up failure
        must degrade to cold first-solve compiles, never block or crash
        the operator."""
        if not self.options.feature_gates.tpu_solver:
            return 0
        fn = getattr(self.tpu, "warmup", None)
        if fn is None:
            return 0
        try:
            return fn(inp, shapes=shapes)
        except Exception as e:  # noqa: BLE001
            from karpenter_tpu.utils.logging import get_logger
            get_logger("solver").warn(
                "solver warm-up failed; first solves compile cold",
                error=str(e)[:200])
            return 0

    def solve_batch(self, inps: List[ScheduleInput],
                    source: str = "disruption",
                    max_nodes: Optional[int] = None):
        """Batched simulations sharing one cluster snapshot (consolidation's
        candidate axis). Returns an iterable: the device path is one eager
        vmapped call; the oracle fallback is LAZY, so a caller that stops at
        the first acceptable result (the disruption loop) never pays for the
        simulations it doesn't consume. Each simulation records one
        observation on the per-simulation duration histogram."""
        import time as _time

        from karpenter_tpu.scheduling import Scheduler
        from karpenter_tpu.solver import UnsupportedPods
        from karpenter_tpu.utils import metrics
        if self.options.feature_gates.tpu_solver:
            try:
                t0 = _time.perf_counter()
                # both backends (in-process TPUSolver, SolverServiceClient)
                # accept the per-call kernel cap
                results = self.tpu.solve_batch(inps, max_nodes=max_nodes)
                if results:
                    per = (_time.perf_counter() - t0) / len(results)
                    for _ in results:
                        metrics.SCHEDULING_SIMULATION_DURATION.observe(per)
                return results
            except UnsupportedPods:
                # per-input retry: each simulation gets its own shot at
                # the device (solve() split-solves inexpressible groups);
                # only truly unsupported inputs reach the oracle inside.
                # The caller's kernel cap rides along — dropping it here
                # would put full-width kernels and the stranded-pod rescue
                # into the consolidation hot loop
                def _per_input():
                    for inp in inps:
                        with metrics.SCHEDULING_SIMULATION_DURATION.time():
                            yield self.solve(inp, source=source,
                                             max_nodes=max_nodes)
                return _per_input()
            except Exception as e:  # noqa: BLE001
                self.cluster.record_event(
                    "Provisioner", source, "SolverFallback", str(e))
                local = self._local_solver()
                if local is not None:
                    try:
                        t0 = _time.perf_counter()
                        results = local.solve_batch(inps,
                                                    max_nodes=max_nodes)
                        if results:
                            per = (_time.perf_counter() - t0) / len(results)
                            for _ in results:
                                metrics.SCHEDULING_SIMULATION_DURATION \
                                    .observe(per)
                        return results
                    except UnsupportedPods:
                        # per-input retry on the LOCAL solver/oracle
                        # only: re-entering self.solve here would pay a
                        # fresh remote retry deadline per input against
                        # the service we just watched fail
                        def _per_input_degraded():
                            for inp in inps:
                                # observe BEFORE yielding: a timer held
                                # across the yield would also clock the
                                # consumer's work (and an abandoned
                                # generator's whole lifetime) into the
                                # simulation histogram
                                t0 = _time.perf_counter()
                                res = self._degraded_solve(
                                    inp, source, max_nodes)
                                if res is None:
                                    metrics.SOLVER_SOLVES.inc(
                                        path="oracle")
                                    res = Scheduler(inp).solve()
                                metrics.SCHEDULING_SIMULATION_DURATION \
                                    .observe(_time.perf_counter() - t0)
                                yield res
                        return _per_input_degraded()
                    except Exception as e2:  # noqa: BLE001
                        from karpenter_tpu.utils.logging import get_logger
                        get_logger("solver").warn(
                            "degraded-mode local batch failed; oracle",
                            source=source, error=str(e2)[:200])

        def _lazy():
            metrics.SOLVER_SOLVES.inc(path="oracle")
            for inp in inps:
                with metrics.SCHEDULING_SIMULATION_DURATION.time():
                    yield Scheduler(inp).solve()
        return _lazy()


def daemon_overhead(cluster: Cluster, pool: NodePool) -> Resources:
    """Aggregate requests of daemonset pods a new node in this pool would
    run (daemonset overhead accounting — SURVEY §2.2 scheduler)."""
    template = pool.template_requirements()
    total = Resources()
    for pod in cluster.daemonset_pods():
        if not tolerates_all(pool.taints, pod.tolerations):
            continue
        if not template.compatible(pod.requirements):
            continue
        total += effective_request(pod)
    return total


def remaining_limit(cluster: Cluster, pool: NodePool,
                    exclude_claims: Set[str] = frozenset()) -> Optional[Resources]:
    if pool.limits is None:
        return None
    used = Resources()
    for claim in cluster.nodeclaims.list(lambda c: c.nodepool == pool.name):
        if claim.name in exclude_claims:
            continue
        # unlaunched claims have no capacity yet — charge their planned
        # requests so stalled launches still hold their limit reservation
        used += (claim.capacity if not claim.capacity.is_zero()
                 else claim.resource_requests)
    return pool.limits - used




def build_existing_nodes(
        cluster: Cluster,
        exclude_nodes: Set[str] = frozenset()) -> List[ExistingNode]:
    """Snapshot every live node as an ExistingNode. The consolidation
    sweep builds this ONCE (no exclusions) and shares the wrapper objects
    across its candidate simulations — both to avoid the O(nodes) rebuild
    per simulation and so the solver's per-batch union cache
    (SharedExistEncoding) can key work by object identity. `exclude_nodes`
    skips candidates BEFORE the resident-pod walk so single-simulation
    callers don't pay for wrappers they immediately discard."""
    existing: List[ExistingNode] = []
    for node in cluster.nodes.list(lambda n: not n.meta.deleting):
        if node.name in exclude_nodes:
            continue
        resident = cluster.pods_on_node(node.name)
        used = Resources()
        for pod in resident:
            used += effective_request(pod)
        existing.append(ExistingNode(
            node=node, available=node.allocatable - used, pods=resident))
    return existing


def build_schedule_input(
    cluster: Cluster,
    cp: TPUCloudProvider,
    pods: List[Pod],
    exclude_nodes: Set[str] = frozenset(),
    exclude_claims: Set[str] = frozenset(),
    price_cap: Optional[float] = None,
    prebuilt_existing: Optional[List[ExistingNode]] = None,
) -> ScheduleInput:
    pools: List[NodePool] = cluster.nodepools.list(
        lambda np_: not np_.meta.deleting)
    # NOTE: price_cap rides on ScheduleInput instead of pre-filtering the
    # type lists — filtering would hand the TPU solver a fresh list object
    # per simulation and thrash its device-resident catalog cache
    instance_types: Dict[str, List[InstanceType]] = {
        p.name: cp.get_instance_types(p.node_class_ref) for p in pools}

    exist_base = None
    exist_excluded = None
    if prebuilt_existing is not None:
        existing = [en for en in prebuilt_existing
                    if en.name not in exclude_nodes]
        # leave-k-out provenance for the batched sweep: the solver encodes
        # the shared snapshot once and expresses this input as exclusion
        # indices on the device (ScheduleInput.exist_base contract)
        exist_base = prebuilt_existing
        exist_excluded = tuple(
            i for i, en in enumerate(prebuilt_existing)
            if en.name in exclude_nodes)
    else:
        existing = build_existing_nodes(cluster, exclude_nodes)

    return ScheduleInput(
        pods=pods,
        nodepools=pools,
        instance_types=instance_types,
        existing_nodes=existing,
        daemon_overhead={p.name: daemon_overhead(cluster, p) for p in pools},
        remaining_limits={
            p.name: remaining_limit(cluster, p, exclude_claims) for p in pools},
        price_cap=price_cap,
        exist_base=exist_base,
        exist_excluded=exist_excluded,
    )
