"""Termination controller — the graceful drain state machine.

Mirrors website/.../disruption.md:29-36 + designs/termination.md: when a
NodeClaim is deleted its finalizer holds it while we (1) taint the node
`karpenter.sh/disrupted:NoSchedule`, (2) evict evictable pods through the
PDB-aware eviction budget (daemonsets stay), (3) once drained, call
CloudProvider.Delete, strip the finalizer, and remove the node object.
Evicted pods return to Pending and re-enter the provisioning queue.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.controllers.provisioning import NOMINATED_ANNOTATION
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import NodeClaim
from karpenter_tpu.models.taints import NO_SCHEDULE, Taint
from karpenter_tpu.utils import errors, metrics

DISRUPTED_TAINT = Taint(wellknown.DISRUPTED_TAINT_KEY, "", NO_SCHEDULE)


class Termination:
    name = "termination"

    def __init__(self, cluster: Cluster, cloud_provider: TPUCloudProvider):
        self.cluster = cluster
        self.cp = cloud_provider

    def reconcile(self) -> None:
        for claim in list(self.cluster.nodeclaims.list(
                lambda c: c.meta.deleting)):
            self._terminate(claim)

    def _terminate(self, claim: NodeClaim) -> None:
        node = self.cluster.node_for_claim(claim)
        if node is not None:
            if not any(t.key == wellknown.DISRUPTED_TAINT_KEY
                       for t in node.taints):
                node.taints.append(DISRUPTED_TAINT)
                self.cluster.nodes.update(node)
            remaining = self._drain(node.name)
            if remaining > 0:
                return  # PDBs throttle the drain; retry next round
        # drained (or node never joined): release the instance + objects.
        # NotFound is success (the instance is already gone); transient cloud
        # errors keep the finalizer for a retry next round
        # (pkg/errors/errors.go taxonomy)
        try:
            self.cp.delete(claim)
        except Exception as e:  # noqa: BLE001
            if errors.is_retryable(e):
                self.cluster.record_event(
                    "NodeClaim", claim.name, "TerminationRetryable", str(e))
                return
            if not errors.is_not_found(e):
                raise
        if node is not None and not node.meta.deleting:
            self.cluster.nodes.delete(node.name)
        self.cluster.nodeclaims.remove_finalizer(
            claim.name, wellknown.TERMINATION_FINALIZER)
        metrics.NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool)
        self.cluster.record_event(
            "NodeClaim", claim.name, "Terminated", "instance released")

    def _drain(self, node_name: str) -> int:
        """Evict what the budgets allow; returns count of pods still to
        evict (excluding daemonsets)."""
        remaining = 0
        for pod in self.cluster.pods_on_node(node_name):
            if pod.is_daemonset:
                continue
            if not self.cluster.can_evict(pod):
                remaining += 1
                continue
            pod.node_name = None
            pod.phase = "Pending"
            pod.meta.annotations.pop(NOMINATED_ANNOTATION, None)
            self.cluster.pods.update(pod)
        return remaining
