"""Termination controller — the graceful drain state machine.

Mirrors website/.../disruption.md:29-36 + designs/termination.md: when a
NodeClaim is deleted its finalizer holds it while we (1) taint the node
`karpenter.sh/disrupted:NoSchedule`, (2) evict evictable pods through the
PDB-aware eviction budget (daemonsets stay), (3) once drained, call
CloudProvider.Delete, strip the finalizer, and remove the node object.
Evicted pods return to Pending and re-enter the provisioning queue.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.controllers.provisioning import NOMINATED_ANNOTATION
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import NodeClaim
from karpenter_tpu.models.taints import NO_SCHEDULE, Taint
from karpenter_tpu.utils import errors, ledger, metrics

DISRUPTED_TAINT = Taint(wellknown.DISRUPTED_TAINT_KEY, "", NO_SCHEDULE)


class Termination:
    name = "termination"

    def __init__(self, cluster: Cluster, cloud_provider: TPUCloudProvider):
        self.cluster = cluster
        self.cp = cloud_provider
        # per-reconcile running fleet $/hr for the ledger: a mass
        # settlement (spot drain, pool expiry sweep) releases many
        # claims in ONE pass, and a per-claim fleet_cost walk would be
        # O(settled × fleet) — interruption's drain-scoped discipline
        self._pass_fleet_cost = None

    def reconcile(self) -> None:
        self._pass_fleet_cost = None
        for claim in list(self.cluster.nodeclaims.list(
                lambda c: c.meta.deleting)):
            self._terminate(claim)

    def _terminate(self, claim: NodeClaim) -> None:
        node = self.cluster.node_for_claim(claim)
        if node is not None:
            if not any(t.key == wellknown.DISRUPTED_TAINT_KEY
                       for t in node.taints):
                node.taints.append(DISRUPTED_TAINT)
                self.cluster.nodes.update(node)
            # terminationGracePeriod (NodePool template): once a deleting
            # claim has waited this long, the drain stops honoring PDBs —
            # the bounded-drain contract; pods are force-evicted and the
            # instance released (reference: NodeClaim terminationGracePeriod)
            force = self._grace_expired(claim)
            remaining = self._drain(node.name, force=force)
            if remaining > 0:
                return  # PDBs throttle the drain; retry next round
        # drained (or node never joined): release the instance + objects.
        # NotFound is success (the instance is already gone); transient cloud
        # errors keep the finalizer for a retry next round
        # (pkg/errors/errors.go taxonomy)
        # ledger inputs BEFORE the release mutates anything: this is the
        # point the fleet's $/hr actually falls for whatever earlier
        # decision (consolidation/expiry/interruption) deleted the claim
        price = fleet_before = None
        if ledger.LEDGER.enabled:
            pricing = getattr(getattr(self.cp, "instance_types", None),
                              "pricing", None)
            price = (ledger.node_price(node, pricing)
                     if node is not None else 0.0)
            if self._pass_fleet_cost is None:
                self._pass_fleet_cost = ledger.fleet_cost(
                    self.cluster, pricing)["total"]
            fleet_before = self._pass_fleet_cost
        try:  # noqa: E501 — see taxonomy note below
            self.cp.delete(claim)
        except Exception as e:  # noqa: BLE001
            if errors.is_retryable(e):
                self.cluster.record_event(
                    "NodeClaim", claim.name, "TerminationRetryable", str(e))
                return
            if not errors.is_not_found(e):
                raise
        if node is not None and not node.meta.deleting:
            self.cluster.nodes.delete(node.name)
        self.cluster.nodeclaims.remove_finalizer(
            claim.name, wellknown.TERMINATION_FINALIZER)
        metrics.NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool)
        self.cluster.record_event(
            "NodeClaim", claim.name, "Terminated", "instance released")
        if fleet_before is not None:
            from karpenter_tpu.solver import explain as explainmod
            # pods_affected=0: the node was drained before release, so
            # no non-daemonset pod is displaced by this settlement
            rec = ledger.record_claim_delete(
                self.cluster, self.cp, claim,
                source="termination",
                reason_code=explainmod.NODE_TERMINATED,
                detail=f"{claim.name} drained and released",
                node=node, price=price, fleet_before=fleet_before,
                pods_affected=0)
            if rec is not None:
                self._pass_fleet_cost += rec.cost_delta

    def _grace_expired(self, claim: NodeClaim) -> bool:
        # stamped on the claim at creation; live-pool fallback covers
        # claims created before the field existed. Claims whose pool was
        # deleted (the gc owner cascade) keep their stamped grace.
        grace = claim.termination_grace_period
        if grace is None:
            pool = self.cluster.nodepools.get(claim.nodepool)
            grace = (pool.termination_grace_period
                     if pool is not None else None)
        if grace is None or claim.meta.deletion_time is None:
            return False
        expired = (self.cluster.clock.now() - claim.meta.deletion_time
                   >= grace)
        if expired:
            self.cluster.record_event(
                "NodeClaim", claim.name, "TerminationGraceElapsed",
                f"draining past terminationGracePeriod={grace}s; "
                "eviction no longer waits for PDBs")
        return expired

    def _drain(self, node_name: str, force: bool = False) -> int:
        """Evict what the budgets allow (everything evictable when
        `force` — grace elapsed); returns count of pods still to evict
        (excluding daemonsets)."""
        remaining = 0
        for pod in self.cluster.pods_on_node(node_name):
            if pod.is_daemonset:
                continue
            if not force and not self.cluster.can_evict(pod):
                remaining += 1
                continue
            pod.node_name = None
            pod.phase = "Pending"
            pod.meta.annotations.pop(NOMINATED_ANNOTATION, None)
            self.cluster.pods.update(pod)
        return remaining
