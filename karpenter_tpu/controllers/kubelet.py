"""FakeKubelet — the node agent the fake cluster needs.

In the reference's tests, envtest has no kubelet: "nodes are just CRs and
the cloud is the fake" (SURVEY §4). This controller plays the kubelet's
observable role so lifecycle semantics are exercised for real: a running
cloud instance joins as a Node (labels from its claim, unregistered taint,
not ready), then goes ready, then sheds startup taints — each on a separate
reconcile round so Launched/Registered/Initialized transitions are
individually observable.
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import Node, ObjectMeta
from karpenter_tpu.models.taints import Taint
from karpenter_tpu.providers.fake_cloud import INSTANCE_RUNNING, TAG_NODECLAIM
from karpenter_tpu.utils import errors, metrics
from karpenter_tpu.utils.logging import get_logger


class FakeKubelet:
    name = "fake-kubelet"

    def __init__(self, cluster: Cluster, cloud_provider: TPUCloudProvider):
        self.cluster = cluster
        self.cp = cloud_provider

    def reconcile(self) -> None:
        try:
            self._reconcile()
        except Exception as e:  # noqa: BLE001 — skip the round on outage
            if not errors.is_retryable(e):
                raise
            get_logger(self.name).warn(
                "kubelet round skipped on retryable error",
                error=str(e)[:200])
            metrics.RECONCILE_ERRORS.inc(controller=self.name)

    def _reconcile(self) -> None:
        for inst in self.cp.list_instances():
            if inst.state != INSTANCE_RUNNING:
                continue
            claim_name = inst.tags.get(TAG_NODECLAIM)
            if claim_name is None:
                continue
            claim = self.cluster.nodeclaims.get(claim_name)
            if claim is None:
                continue
            node = self.cluster.node_for_claim(claim)
            if node is None:
                self._join(claim, inst)
            elif not node.ready:
                node.ready = True
                self.cluster.nodes.update(node)
            else:
                self._shed_startup_taints(claim, node)

    def _join(self, claim, inst) -> None:
        labels = {}
        for req in claim.requirements:
            if req.is_finite() and len(req.values()) == 1:
                (labels[req.key],) = req.values()
        labels[wellknown.NODEPOOL_LABEL] = claim.nodepool
        labels[wellknown.HOSTNAME_LABEL] = claim.name
        node = Node(
            meta=ObjectMeta(name=claim.name, labels=labels),
            provider_id=inst.instance_id,
            capacity=claim.capacity.copy(),
            allocatable=claim.allocatable.copy(),
            taints=(list(claim.taints) + list(claim.startup_taints)
                    + [Taint(wellknown.UNREGISTERED_TAINT_KEY)]),
            ready=False,
        )
        try:
            self.cluster.nodes.create(node)
        except ValueError:
            # AlreadyExists: a replica losing leadership can race its
            # successor inside the brief dual-writer window (k8s absorbs
            # this as an apiserver 409) — the node is joined either way
            pass

    def _shed_startup_taints(self, claim, node) -> None:
        """One reconcile round after readiness, the 'CNI-style' agents the
        startup taints wait for come up and remove them."""
        startup_keys = {t.key for t in claim.startup_taints}
        if not startup_keys:
            return
        before = len(node.taints)
        node.taints = [t for t in node.taints if t.key not in startup_keys]
        if len(node.taints) != before:
            self.cluster.nodes.update(node)
