"""Disruption controller — drift, emptiness, and consolidation.

The second hot path (SURVEY §3.3). Flow per disruption.md:14-27: build
candidates from cluster state → budget check → scheduling SIMULATION →
taint → pre-spin replacement → wait Ready → delete. Methods run in order
Drift → Emptiness → Multi-node consolidation → Single-node consolidation
(disruption.md:90-101), one command at a time.

Candidate ranking follows designs/consolidation.md:25-42: disruption cost =
Σ over evictable pods of (1 + deletion-cost & priority weights), scaled by
the node's remaining lifetime fraction (1.0 at creation → 0.0 at expiry).

Consolidation decisions:
  delete   — candidate's pods fit on the remaining nodes, no new capacity
  replace  — pods fit with exactly ONE new node strictly cheaper than the
             candidates it replaces; spot→spot additionally requires ≥15
             instance-type flexibility in the replacement
             (disruption.md:123-132) and its feature gate
Multi-node tries the cheapest-to-disrupt prefix of candidates first and
shrinks until feasible (the reference's heuristic subset search).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.controllers.provisioning import create_claim_from_spec
from karpenter_tpu.controllers.state import (GatedSolver,
                                             build_existing_nodes,
                                             build_schedule_input)
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import (
    CONSOLIDATE_WHEN_EMPTY,
    CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED,
    COND_INITIALIZED,
    Node,
    NodeClaim,
    NodePool,
)
from karpenter_tpu.models.taints import NO_SCHEDULE, Taint
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling import ScheduleResult
from karpenter_tpu.scheduling.types import ScheduleInput
from karpenter_tpu.solver import explain as explainmod
from karpenter_tpu.solver.solve import B_BUCKETS as SOLVER_B_BUCKETS
from karpenter_tpu.utils import cron, errors, ledger, metrics, tracing
from karpenter_tpu.utils.clock import Clock

SPOT_TO_SPOT_MIN_TYPES = 15  # disruption.md:123-132

REASON_DRIFT = "Drifted"
REASON_EMPTY = "Empty"
REASON_UNDERUTILIZED = "Underutilized"

DISRUPTING_TAINT = Taint(wellknown.DISRUPTION_TAINT_KEY, "disrupting",
                         NO_SCHEDULE)


@dataclass
class Candidate:
    claim: NodeClaim
    node: Node
    pool: NodePool
    reschedulable: List = field(default_factory=list)  # non-daemon pods
    price: float = 0.0
    cost: float = 0.0  # disruption cost for ranking


@dataclass
class Command:
    """An in-flight disruption: replacements must initialize before the
    candidates are deleted (pre-spin — disruption.md:14-27)."""
    reason: str
    candidate_names: List[str]
    replacement_names: List[str]
    started: float


class Disruption:
    name = "disruption"

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: TPUCloudProvider,
        options: Optional[Options] = None,
        clock: Optional[Clock] = None,
        solver: Optional[GatedSolver] = None,
    ):
        self.cluster = cluster
        self.cp = cloud_provider
        self.options = options or Options()
        self.clock = clock or cluster.clock
        self.solver = solver or GatedSolver(self.options, cluster)
        self.commands: List[Command] = []
        self._replacement_seq = 0
        self.command_timeout = 10 * 60.0
        # replacements stay off the candidate list until pods land on them
        # (or the grace period lapses) — otherwise the emptiness method can
        # delete a just-initialized replacement before evicted pods rebind
        self._protected: Dict[str, float] = {}
        self.protection_grace = 5 * 60.0

    # ------------------------------------------------------------------
    def reconcile(self) -> None:
        try:
            self._reconcile()
        except Exception as e:  # noqa: BLE001 — cloud outage: skip the pass
            if not errors.is_retryable(e):
                raise
            from karpenter_tpu.utils.logging import get_logger
            get_logger(self.name).warn(
                "disruption pass skipped on retryable error",
                error=str(e)[:200])
            metrics.RECONCILE_ERRORS.inc(controller=self.name)

    def _reconcile(self) -> None:
        if self._process_commands():
            return  # one in-flight command at a time (minimal-change bias)
        candidates = self._build_candidates()
        self._publish_eligibility(candidates)
        if not candidates:
            return
        # one trace per disruption pass: each method's evaluation (and the
        # batched simulations under it) nests here, mirroring the
        # provisioning pass's root span
        with tracing.span("disruption.pass",
                          candidates=len(candidates)) as _sp:
            for method in (self._drift, self._emptiness,
                           self._multi_node, self._single_node):
                mname = method.__name__.lstrip("_")
                with metrics.DISRUPTION_EVALUATION_DURATION.time(
                        method=mname):
                    with tracing.span(f"disruption.{mname}"):
                        acted = method(candidates)
                if acted:
                    metrics.DISRUPTION_ACTIONS.inc(method=mname)
                    if _sp is not None:
                        _sp.attrs["acted"] = mname
                    return

    def _publish_eligibility(self, candidates: List[Candidate]) -> None:
        """Refresh every method's eligible-nodes gauge each pass (including
        to zero) so the exported values never go stale."""
        consolidatable = self._consolidatable(candidates)
        empty = [c for c in candidates if not c.reschedulable]
        metrics.DISRUPTION_ELIGIBLE_NODES.set(len(candidates), method="drift")
        metrics.DISRUPTION_ELIGIBLE_NODES.set(len(empty), method="emptiness")
        metrics.DISRUPTION_ELIGIBLE_NODES.set(
            len(consolidatable), method="multi_node")
        metrics.DISRUPTION_ELIGIBLE_NODES.set(
            len(consolidatable), method="single_node")

    # -- in-flight commands ----------------------------------------------
    def _process_commands(self) -> bool:
        still: List[Command] = []
        for cmd in self.commands:
            done, abort = self._command_state(cmd)
            if done:
                for name in cmd.candidate_names:
                    self.cluster.nodeclaims.delete(name)
                self.cluster.record_event(
                    "Disruption", ",".join(cmd.candidate_names),
                    f"Disrupted{cmd.reason}",
                    f"replacements: {cmd.replacement_names or 'none'}")
            elif abort:
                self._abort(cmd)
            else:
                still.append(cmd)
        self.commands = still
        return bool(still)

    def _command_state(self, cmd: Command) -> tuple:
        if self.clock.now() - cmd.started > self.command_timeout:
            return False, True
        for name in cmd.replacement_names:
            rep = self.cluster.nodeclaims.get(name)
            if rep is None:
                return False, True  # replacement failed terminally
            if not rep.is_(COND_INITIALIZED):
                return False, False
        return True, False

    def _abort(self, cmd: Command) -> None:
        for name in cmd.replacement_names:
            self.cluster.nodeclaims.delete(name)
        for name in cmd.candidate_names:
            claim = self.cluster.nodeclaims.get(name)
            node = self.cluster.node_for_claim(claim) if claim else None
            if node is not None:
                node.taints = [t for t in node.taints
                               if t.key != wellknown.DISRUPTION_TAINT_KEY]
                self.cluster.nodes.update(node)
        self.cluster.record_event(
            "Disruption", ",".join(cmd.candidate_names),
            "DisruptionAborted", cmd.reason)

    # -- candidates -------------------------------------------------------
    def _build_candidates(self) -> List[Candidate]:
        out: List[Candidate] = []
        in_flight = {n for cmd in self.commands for n in cmd.candidate_names}
        now = self.clock.now()
        # drop stale protections (claim gone, grace lapsed, or pods landed)
        for name, t in list(self._protected.items()):
            claim = self.cluster.nodeclaims.get(name)
            if claim is None or now - t > self.protection_grace:
                del self._protected[name]
            elif claim.node_name and self.cluster.pods_on_node(claim.node_name):
                del self._protected[name]
        for claim in self.cluster.nodeclaims.list():
            if claim.meta.deleting or claim.name in in_flight:
                continue
            if claim.name in self._protected:
                continue  # fresh replacement: evicted pods haven't landed yet
            if not claim.is_(COND_INITIALIZED):
                continue
            node = self.cluster.node_for_claim(claim)
            if node is None or node.meta.deleting or not node.ready:
                continue
            # the do-not-disrupt annotation blocks voluntary disruption at
            # the node/claim level too, not just per pod (reference:
            # disruption.md — karpenter.sh/do-not-disrupt on the node)
            if any(o.meta.annotations.get(
                    wellknown.DO_NOT_DISRUPT_ANNOTATION) == "true"
                   for o in (node, claim)):
                continue
            pool = self.cluster.nodepools.get(claim.nodepool)
            if pool is None:
                continue
            # minimum settle time before consolidation (consolidate_after)
            settle = pool.disruption.consolidate_after
            if claim.launch_time is not None and now - claim.launch_time < settle:
                continue
            pods = self.cluster.pods_on_node(node.name)
            resched = [p for p in pods if not p.is_daemonset]
            # blocking pods (designs/consolidation.md:46-52)
            if any(p.do_not_disrupt() or p.owner_kind is None
                   or not self.cluster.can_evict(p) for p in resched):
                continue
            out.append(Candidate(
                claim=claim, node=node, pool=pool, reschedulable=resched,
                price=self._node_price(claim, node),
                cost=self._disruption_cost(claim, pool, resched, now),
            ))
        out.sort(key=lambda c: c.cost)
        return out

    def _node_price(self, claim: NodeClaim, node: Node) -> float:
        itype = node.instance_type
        zone = node.zone
        ct = node.capacity_type
        if itype and zone and ct:
            p = self.cp.instance_types.pricing.price(itype, zone, ct)
            if p is not None:
                return p
        return 0.0

    def _disruption_cost(self, claim: NodeClaim, pool: NodePool,
                         pods: List, now: float) -> float:
        base = sum(
            1.0 + max(p.priority, 0) / 1e6 + p.deletion_cost() / 1e3
            for p in pods)
        lifetime = 1.0
        if pool.expire_after and claim.launch_time is not None:
            remaining = pool.expire_after - (now - claim.launch_time)
            lifetime = max(0.0, min(1.0, remaining / pool.expire_after))
        return base * lifetime

    # -- budgets ----------------------------------------------------------
    def _budget_allows(self, pool: NodePool, reason: str, want: int) -> int:
        total = len([
            c for c in self.cluster.nodeclaims.list(
                lambda c: c.nodepool == pool.name)
        ])
        disrupting = len([
            c for c in self.cluster.nodeclaims.list(
                lambda c: c.nodepool == pool.name and c.meta.deleting)
        ]) + sum(
            1 for cmd in self.commands for n in cmd.candidate_names
            if (cl := self.cluster.nodeclaims.get(n)) is not None
            and cl.nodepool == pool.name)
        allowed = None
        for budget in pool.disruption.budgets:
            if budget.reasons is not None and reason not in budget.reasons:
                continue
            # cron-windowed budgets only bind while their window is open
            # (schedule fires in UTC; active for `duration` seconds). An
            # unparseable schedule fails SAFE: the budget binds — a typo
            # must neither drop a configured freeze nor kill the operator
            try:
                if not cron.in_window(budget.schedule, budget.duration,
                                      self.clock.now()):
                    continue
            except cron.CronError as e:
                self.cluster.record_event(
                    "NodePool", pool.name, "InvalidBudgetSchedule", str(e))
            a = budget.allowed_disruptions(total)
            allowed = a if allowed is None else min(allowed, a)
        if allowed is None:
            allowed = total
        return max(0, min(want, allowed - disrupting))

    # -- methods ----------------------------------------------------------
    def _drift(self, candidates: List[Candidate]) -> bool:
        if not self.options.feature_gates.drift:
            return False
        for cand in candidates:
            reason = self._drift_reason(cand)
            if reason is None:
                continue
            if self._budget_allows(cand.pool, REASON_DRIFT, 1) < 1:
                self.cluster.record_event(
                    "NodeClaim", cand.claim.name, "DisruptionBlocked",
                    explainmod.make(
                        explainmod.BUDGET_BLOCKED,
                        f"drift of {cand.claim.name} blocked by "
                        f"nodepool {cand.pool.name}'s disruption budget"))
                continue
            # drifted capacity is replaced in kind: feasibility simulation
            # without the cheaper-price requirement
            sim = self._simulate([cand], price_cap=None)
            if sim is None:
                self.cluster.record_event(
                    "NodeClaim", cand.claim.name, "Undisruptable",
                    explainmod.make(
                        explainmod.CANDIDATE_NOT_RESCHEDULABLE,
                        "drifted but pods cannot reschedule"))
                continue
            self._execute(REASON_DRIFT, [cand], sim, method="drift")
            return True
        return False

    def _drift_reason(self, cand: Candidate) -> Optional[str]:
        pool_hash = cand.pool.static_hash()
        stamped = cand.claim.meta.annotations.get(
            wellknown.NODEPOOL_HASH_ANNOTATION)
        if stamped is not None and stamped != pool_hash:
            return explainmod.make(
                explainmod.NODEPOOL_DRIFT,
                "NodePoolDrift: stamped hash no longer matches the pool")
        return self.cp.is_drifted(cand.claim)

    def _emptiness(self, candidates: List[Candidate]) -> bool:
        empty = [c for c in candidates if not c.reschedulable
                 and c.pool.disruption.consolidation_policy in (
                     CONSOLIDATE_WHEN_EMPTY,
                     CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED)]
        if not empty:
            return False
        by_pool: Dict[str, List[Candidate]] = {}
        for c in empty:
            by_pool.setdefault(c.pool.name, []).append(c)
        acted = False
        for pool_name, cands in by_pool.items():
            n = self._budget_allows(cands[0].pool, REASON_EMPTY, len(cands))
            deleted = cands[:n]
            for cand in deleted:
                self.cluster.record_event(
                    "NodeClaim", cand.claim.name, "DisruptedEmpty", "")
                self.cluster.nodeclaims.delete(cand.claim.name)
                acted = True
            if deleted:
                # empty deletes are pure savings: the ledger delta is the
                # exact sum of retired prices, and the savings counter
                # carries the same floats (IEEE-exactness contract)
                retired = sum(c.price for c in deleted)
                self._ledger_decision(
                    "disruption", "delete",
                    explainmod.CONSOLIDATION_DELETE, deleted, (),
                    cost_delta=-retired,
                    detail=f"{len(deleted)} empty node(s) in "
                           f"{pool_name} deleted")
                if retired > 0:
                    metrics.DISRUPTION_SAVINGS.inc(
                        retired, method="emptiness")
        return acted

    def _consolidatable(self, candidates: List[Candidate]) -> List[Candidate]:
        return [
            c for c in candidates
            if c.pool.disruption.consolidation_policy
            == CONSOLIDATE_WHEN_EMPTY_OR_UNDERUTILIZED
            and c.reschedulable  # empties are handled by emptiness
        ]

    # candidate sets per batched simulation call: one device call evaluates
    # the whole chunk — linked to the solver's largest batch bucket so a
    # disruption chunk never splits into multiple device calls
    SIM_CHUNK = SOLVER_B_BUCKETS[-1]

    def _multi_node(self, candidates: List[Candidate]) -> bool:
        cands = self._consolidatable(candidates)
        if len(cands) < 2:
            return False
        # shrink the cheapest-to-disrupt prefix until feasible, largest
        # prefix first (the reference's heuristic subset search) — all
        # prefix simulations batch onto the device in chunks
        subsets: List[List[Candidate]] = []
        for k in range(len(cands), 1, -1):
            subset = cands[:k]
            # budgets are per pool over the WHOLE subset — each pool must
            # allow as many concurrent disruptions as the subset takes
            per_pool: Dict[str, int] = {}
            for c in subset:
                per_pool[c.pool.name] = per_pool.get(c.pool.name, 0) + 1
            pools = {c.pool.name: c.pool for c in subset}
            if any(self._budget_allows(pools[name], REASON_UNDERUTILIZED, n) < n
                   for name, n in per_pool.items()):
                continue
            subsets.append(subset)
        for start in range(0, len(subsets), self.SIM_CHUNK):
            chunk = subsets[start:start + self.SIM_CHUNK]
            sims = self._simulate_batch(
                chunk, [sum(c.price for c in s) for s in chunk])
            for subset, sim in zip(chunk, sims):
                if sim is not None and self._acceptable(subset, sim):
                    self._execute(REASON_UNDERUTILIZED, subset, sim,
                                  method="multi_node")
                    return True
        return False

    def _single_node(self, candidates: List[Candidate]) -> bool:
        cands = [c for c in self._consolidatable(candidates)
                 if self._budget_allows(c.pool, REASON_UNDERUTILIZED, 1) >= 1]
        for start in range(0, len(cands), self.SIM_CHUNK):
            chunk = cands[start:start + self.SIM_CHUNK]
            sims = self._simulate_batch(
                [[c] for c in chunk], [c.price for c in chunk])
            for cand, sim in zip(chunk, sims):
                reason = (explainmod.make(
                    explainmod.CANDIDATE_NOT_RESCHEDULABLE,
                    "pods cannot reschedule onto remaining capacity "
                    "or a single cheaper node") if sim is None
                    else self._unacceptable_reason([cand], sim))
                if reason is None:
                    self._execute(REASON_UNDERUTILIZED, [cand], sim,
                                  method="single_node")
                    return True
                # user-facing reason a node stays up (disruption.md:109-117
                # Unconsolidatable events; the recorder deduplicates)
                self.cluster.record_event(
                    "NodeClaim", cand.claim.name, "Unconsolidatable", reason)
        return False

    # -- simulation -------------------------------------------------------
    def _build_sim_input(self, cands: List[Candidate],
                         price_cap: Optional[float],
                         prebuilt=None) -> ScheduleInput:
        pods = [p for c in cands for p in c.reschedulable]
        exclude = {c.node.name for c in cands}
        exclude_claims = {c.claim.name for c in cands}
        return build_schedule_input(
            self.cluster, self.cp, pods,
            exclude_nodes=exclude, exclude_claims=exclude_claims,
            price_cap=price_cap, prebuilt_existing=prebuilt)

    @staticmethod
    def _admissible(result: ScheduleResult) -> Optional[ScheduleResult]:
        if result.unschedulable:
            return None
        if len(result.new_claims) > 1:
            return None  # minimal change: at most one replacement node
        return result

    def _simulate(self, cands: List[Candidate],
                  price_cap: Optional[float]) -> Optional[ScheduleResult]:
        """Can the candidates' pods run on the remaining nodes, plus at most
        one new (price-capped) node? None = infeasible."""
        inp = self._build_sim_input(cands, price_cap)
        with metrics.SCHEDULING_SIMULATION_DURATION.time():
            with tracing.span("disruption.simulate", pods=len(inp.pods)):
                return self._admissible(self.solver.solve(
                    inp, source="disruption", max_nodes=8))

    def _simulate_batch(self, cand_sets: List[List[Candidate]],
                        price_caps: List[Optional[float]]):
        """Lazy iterator of admissible results: with the oracle fallback the
        underlying solve runs per-consumed item, so a caller that acts on
        the first acceptable candidate pays for exactly the simulations it
        looked at (per-simulation metrics recorded in GatedSolver)."""
        # one node snapshot shared by every simulation: wrappers are
        # reused, so the controller-side build is O(nodes + sims) and the
        # solver's per-batch union cache keys work by object identity
        with tracing.span("disruption.simulate_batch",
                          sims=len(cand_sets)):
            prebuilt = build_existing_nodes(self.cluster)
            inps = [self._build_sim_input(cs, cap, prebuilt=prebuilt)
                    for cs, cap in zip(cand_sets, price_caps)]
            # admissibility allows at most ONE replacement node
            # (_admissible), so a tiny new-node axis is exact: slot
            # exhaustion reports unschedulable, rejected the same as a
            # >1-claim result
            results = self.solver.solve_batch(inps, source="disruption",
                                              max_nodes=8)
        return (self._admissible(r) for r in results)

    def _acceptable(self, cands: List[Candidate],
                    sim: ScheduleResult) -> bool:
        return self._unacceptable_reason(cands, sim) is None

    def _unacceptable_reason(self, cands: List[Candidate],
                             sim: ScheduleResult) -> Optional[str]:
        """None = acceptable; else a registry-coded Reason
        (solver/explain.py — the ledger stores the code, the event keeps
        the human detail; the accurate message matters: pointing an
        operator at pricing when the spot-flexibility rule is what
        blocked the replacement sends the debugging in the wrong
        direction)."""
        if not sim.new_claims:
            return None  # pure delete: always saves money
        total_price = sum(c.price for c in cands)
        rep = sim.new_claims[0]
        if rep.price >= total_price:
            return explainmod.make(
                explainmod.REPLACEMENT_NOT_CHEAPER,
                "replacement would not reduce cost")
        # spot→spot: replacement must keep ≥15 types of flexibility so it
        # lands on reliable spot capacity (disruption.md:123-132)
        all_spot = all(
            c.node.capacity_type == wellknown.CAPACITY_TYPE_SPOT for c in cands)
        rep_ct = rep.requirements.get(wellknown.CAPACITY_TYPE_LABEL)
        rep_spot = rep_ct is not None and rep_ct.is_finite() \
            and rep_ct.values() == {wellknown.CAPACITY_TYPE_SPOT}
        rep_spot = rep_spot or (rep_ct is None)
        if all_spot and rep_spot:
            if not self.options.feature_gates.spot_to_spot_consolidation:
                return explainmod.make(
                    explainmod.SPOT_TO_SPOT_DISABLED,
                    "spot-to-spot consolidation is disabled "
                    "(SpotToSpotConsolidation feature gate)")
            if len(rep.instance_type_names) < SPOT_TO_SPOT_MIN_TYPES:
                return explainmod.make(
                    explainmod.SPOT_FLEXIBILITY_TOO_LOW,
                    f"spot-to-spot replacement keeps only "
                    f"{len(rep.instance_type_names)} instance types of "
                    f"the {SPOT_TO_SPOT_MIN_TYPES} required for "
                    f"reliable spot capacity")
        return None

    # -- execution --------------------------------------------------------
    def _ledger_decision(self, source: str, action: str, code: str,
                         cands: List[Candidate], new_claims,
                         cost_delta: float, detail: str = "") -> None:
        """One decision-ledger record for this controller's fleet
        mutation: the exact price arithmetic the decision compared,
        before/after fleet $/hr from the independent node sum, and the
        trace/flight cross-links (utils/ledger.py stamps those)."""
        if not ledger.LEDGER.enabled:
            return
        pricing = getattr(self.cp.instance_types, "pricing", None)
        before = ledger.fleet_cost(self.cluster, pricing)["total"]
        ledger.LEDGER.record(
            source, action, reason_code=code, detail=detail,
            pools=[c.pool.name for c in cands]
            + [s.nodepool for s in new_claims],
            capacity_types=[ct for c in cands
                            if (ct := c.node.capacity_type)],
            nodes_delta=len(new_claims) - len(cands),
            pods_affected=sum(len(c.reschedulable) for c in cands),
            fleet_cost_before=before, cost_delta=cost_delta)

    def _execute(self, reason: str, cands: List[Candidate],
                 sim: ScheduleResult, method: str = "single_node") -> None:
        for cand in cands:
            if not any(t.key == wellknown.DISRUPTION_TAINT_KEY
                       for t in cand.node.taints):
                cand.node.taints.append(DISRUPTING_TAINT)
                self.cluster.nodes.update(cand.node)
        replacements = []
        for spec in sim.new_claims:
            self._replacement_seq += 1
            claim = create_claim_from_spec(
                self.cluster, self.cp, spec,
                f"{spec.nodepool}-replace-{self._replacement_seq}")
            replacements.append(claim.name)
            self._protected[claim.name] = self.clock.now()
        # decision ledger + savings: recorded at DECISION time with the
        # exact floats this method compared — savings is (sum of retired
        # candidate prices − replacement price), the IEEE-exactness
        # contract the config4 bench asserts.  Drift replaces in kind
        # (no cheaper-price rule), so it writes a ledger record but
        # never claims savings.
        retired = sum(c.price for c in cands)
        added = sum(s.price for s in sim.new_claims)
        if reason == REASON_DRIFT:
            source, code = "drift", explainmod.DRIFT_REPLACED
        elif sim.new_claims:
            source, code = "disruption", explainmod.CONSOLIDATION_REPLACE
        else:
            source, code = "disruption", explainmod.CONSOLIDATION_DELETE
        self._ledger_decision(
            source, "replace" if sim.new_claims else "delete", code,
            cands, sim.new_claims, cost_delta=added - retired,
            detail=f"{method}: {len(cands)} candidate(s) -> "
                   f"{len(replacements)} replacement(s)")
        savings = retired - added
        if reason != REASON_DRIFT and savings > 0:
            metrics.DISRUPTION_SAVINGS.inc(savings, method=method)
        self.commands.append(Command(
            reason=reason,
            candidate_names=[c.claim.name for c in cands],
            replacement_names=replacements,
            started=self.clock.now(),
        ))
