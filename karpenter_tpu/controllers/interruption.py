"""Interruption controller — spot reclaim / health events → proactive drain.

Mirrors pkg/controllers/interruption/controller.go:86-126: drain the
interruption queue via the queue provider, match messages to NodeClaims by
instance id (:148-173), act per message kind
(pkg/controllers/interruption/messages/*):

  spot_interruption        mark the offering unavailable (feeding the
                           scheduler's ICE cache, :202-208) and delete the
                           claim so termination drains it ahead of the
                           2-minute reclaim (designs/interruption-handling.md)
  rebalance_recommendation advisory only — event, no action (the reference
                           only acts on these behind explicit opt-in)
  scheduled_change         cloud maintenance: delete the claim
  state_change             stopping/terminated out from under us: delete
"""

from __future__ import annotations

from karpenter_tpu.cluster import Cluster
from karpenter_tpu.providers.queue import QueueProvider
from karpenter_tpu.utils import errors, ledger, metrics
from karpenter_tpu.utils.cache import UnavailableOfferings


class Interruption:
    name = "interruption"

    def __init__(self, cluster: Cluster, queue: QueueProvider,
                 unavailable: UnavailableOfferings, cloud_provider=None):
        self.cluster = cluster
        self.queue = queue
        self.unavailable = unavailable
        # optional: only the decision ledger's pricing lookups need it
        self.cp = cloud_provider
        self._drain_fleet_cost = None  # per-reconcile running total
        self._drain_cache: dict = {}   # per-reconcile pods-by-node index

    # long-poll batches drained per reconcile: the reference requeues
    # immediately after each poll (controller.go:124 — effectively a
    # continuous drain); a bounded in-reconcile drain gets the same
    # throughput without starving the other controllers in our
    # single-threaded manager
    MAX_BATCHES_PER_RECONCILE = 1000

    def reconcile(self) -> None:
        by_pid = None
        # per-drain ledger state: a mass reclaim deletes hundreds of
        # claims in one reconcile, and re-walking the fleet per record
        # would be O(deleted x fleet) — the sum is computed once at the
        # first reclaim and advanced by each record's own delta; the
        # pods-by-node index amortizes the pod count the same way
        self._drain_fleet_cost = None
        self._drain_cache = {}
        for _ in range(self.MAX_BATCHES_PER_RECONCILE):
            try:
                msgs = list(self.queue.receive())
            except Exception as e:  # noqa: BLE001 — outage: poll next round
                if not errors.is_retryable(e):
                    raise
                from karpenter_tpu.utils.logging import get_logger
                get_logger(self.name).warn(
                    "interruption queue poll failed; retry next round",
                    error=str(e)[:200])
                metrics.RECONCILE_ERRORS.inc(controller=self.name)
                return
            if not msgs:
                return
            if by_pid is None:
                # ONE claim index per drain: messages only ever REMOVE
                # claims, so the index stays valid across batches —
                # rebuilding per 20-message poll is quadratic at benchmark
                # volumes (interruption_benchmark_test.go drives 15k)
                by_pid = {c.provider_id: c
                          for c in self.cluster.nodeclaims.list()
                          if c.provider_id}
            for msg in msgs:
                self._handle(msg, by_pid)
                self.queue.delete(msg)

    def _handle(self, msg: dict, by_pid=None) -> None:
        metrics.INTERRUPTION_MESSAGES.inc(
            message_type=msg.get("kind", "unknown"))
        instance_id = msg.get("instance_id")
        if by_pid is not None:
            claim = by_pid.get(instance_id)
        else:
            claim = next(
                (c for c in self.cluster.nodeclaims.list()
                 if c.provider_id == instance_id), None)
        kind = msg.get("kind")
        if kind == "spot_interruption":
            # the timeline's spot.reclaim capture point — one event per
            # reclaim message, cross-linked to the claim it takes down
            from karpenter_tpu.timeline import events as tev
            from karpenter_tpu.timeline import recorder as trec
            trec.emit(tev.SPOT_RECLAIM, name=str(instance_id or ""),
                      data={"claim": claim.name if claim else None})
            inst = self.queue.cloud.instances.get(instance_id)
            if inst is not None:
                # the reclaimed pool is unavailable for the next 3 minutes —
                # the scheduler must not immediately relaunch into it
                self.unavailable.mark_unavailable(
                    inst.capacity_type, inst.instance_type, inst.zone,
                    reason="SpotInterruption")
                # feed the spot-risk model (ISSUE 16): one observed
                # reclaim raises this pool's interruption probability and
                # bumps the model version, so the next solve re-ranks
                # against the new reality
                from karpenter_tpu.scheduling import risk
                risk.observe_interruption(inst.instance_type, inst.zone)
            if claim is not None:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "SpotInterrupted",
                    f"instance {instance_id} reclaim imminent")
                self._delete_claim(claim, by_pid, instance_id)
        elif kind == "rebalance_recommendation":
            if claim is not None:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "RebalanceRecommendation",
                    f"instance {instance_id} at elevated reclaim risk")
        elif kind == "scheduled_change":
            if claim is not None:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "ScheduledChange",
                    "cloud maintenance event")
                self._delete_claim(claim, by_pid, instance_id)
        elif kind == "state_change":
            if msg.get("state") in ("stopping", "stopped", "terminated") \
                    and claim is not None:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "InstanceStateChange",
                    msg.get("state", ""))
                self._delete_claim(claim, by_pid, instance_id)

    def _delete_claim(self, claim, by_pid, instance_id) -> None:
        """Delete + drop from the drain index: a duplicate message for the
        same instance later in the drain must see the claim gone, exactly
        as a fresh informer read would."""
        self._ledger_reclaim(claim, instance_id)
        self.cluster.nodeclaims.delete(claim.name)
        if by_pid is not None:
            by_pid.pop(instance_id, None)

    def _ledger_reclaim(self, claim, instance_id) -> None:
        """One decision-ledger record per interruption-driven delete: the
        reclaimed node's $/hr leaves the fleet (the replacement shows up
        as a later provisioning launch record).  The fleet sum is the
        drain-scoped running total, never a per-record fleet walk."""
        if not ledger.LEDGER.enabled:
            return
        from karpenter_tpu.solver import explain as explainmod
        if self._drain_fleet_cost is None:
            pricing = getattr(getattr(self.cp, "instance_types", None),
                              "pricing", None)
            self._drain_fleet_cost = ledger.fleet_cost(
                self.cluster, pricing)["total"]
        rec = ledger.record_claim_delete(
            self.cluster, self.cp, claim,
            source="interruption",
            reason_code=explainmod.INTERRUPTION_RECLAIM,
            detail=f"instance {instance_id} reclaim/maintenance",
            fleet_before=self._drain_fleet_cost,
            pass_cache=self._drain_cache)
        if rec is not None:
            self._drain_fleet_cost += rec.cost_delta
