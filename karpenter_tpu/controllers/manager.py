"""Deterministic controller manager.

The reference runs controllers on watch-driven workqueues under a
controller-runtime manager with leader election (cmd/controller/main.go:73).
Our in-process analogue runs each controller's reconcile() in rounds until
the cluster reaches a fixed point — equivalent observable behavior, fully
deterministic for tests (the role envtest + eventually() plays in the
reference's suites).
"""

from __future__ import annotations

from typing import List, Protocol

from karpenter_tpu.cluster import Cluster


class Controller(Protocol):
    name: str

    def reconcile(self) -> None: ...


class ControllerManager:
    def __init__(self, cluster: Cluster, controllers: List[Controller]):
        self.cluster = cluster
        self.controllers = list(controllers)

    def run_once(self) -> None:
        # peer replicas' writes land in the informer cache before any
        # reconciler reads it (no-op on the in-memory backend)
        self.cluster.sync_backend()
        for c in self.controllers:
            c.reconcile()

    def run_until_idle(self, max_rounds: int = 50) -> int:
        """Reconcile all controllers until nothing mutates the cluster.
        Returns the number of rounds taken; raises if no fixed point is
        reached (a reconcile livelock is a bug)."""
        for round_ in range(max_rounds):
            gen = self.cluster.generation
            self.run_once()
            if self.cluster.generation == gen:
                return round_ + 1
        raise RuntimeError(
            f"controllers did not settle in {max_rounds} rounds")
