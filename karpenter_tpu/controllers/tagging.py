"""NodeClaim tagging controller.

Mirrors pkg/controllers/nodeclaim/tagging/controller.go:56-119: once a
NodeClaim registers (its node joined), tag the backing instance with the
claim/node identity so cloud-side inventory tooling can attribute it.
Tagging is post-registration because the node name only exists then.
"""

from __future__ import annotations

from karpenter_tpu.cluster import Cluster
from karpenter_tpu.models.objects import COND_REGISTERED
from karpenter_tpu.utils import errors, metrics
from karpenter_tpu.utils.logging import get_logger

TAG_NAME = "Name"
TAG_MANAGED_BY = "karpenter.tpu/managed-by"


class NodeClaimTagging:
    name = "nodeclaim-tagging"

    def __init__(self, cluster: Cluster, cloud,
                 cluster_name: str = "default-cluster"):
        self.cluster = cluster
        self.cloud = cloud
        self.cluster_name = cluster_name

    def reconcile(self) -> None:
        try:
            self._reconcile()
        except Exception as e:  # noqa: BLE001 — tagging is cosmetic; retry
            if not errors.is_retryable(e):
                raise
            get_logger(self.name).warn(
                "tagging pass skipped on retryable error",
                error=str(e)[:200])
            metrics.RECONCILE_ERRORS.inc(controller=self.name)

    def _reconcile(self) -> None:
        for claim in self.cluster.nodeclaims.list():
            if not claim.is_(COND_REGISTERED) or not claim.provider_id:
                continue
            inst = self.cloud.get_instance(claim.provider_id)
            if inst is None or TAG_NAME in inst.tags:
                continue
            self.cloud.create_tags(claim.provider_id, {
                TAG_NAME: claim.node_name or claim.name,
                TAG_MANAGED_BY: self.cluster_name,
            })
