"""NodeClaim lifecycle: Launch → Register → Initialize (+ liveness GC).

Mirrors the core node-lifecycle controller (SURVEY §2.2: metrics
karpenter_nodeclaims_{launched,registered,initialized}; liveness: claims
never registered within 15 min are garbage-collected —
designs/limits.md:23-25).
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider import (
    CloudProviderError,
    InsufficientCapacity,
    NodeClassNotReady,
    TPUCloudProvider,
)
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
)
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils import errors, metrics, tracing
from karpenter_tpu.utils.clock import Clock


class NodeClaimLifecycle:
    name = "nodeclaim.lifecycle"

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: TPUCloudProvider,
        options: Optional[Options] = None,
        clock: Optional[Clock] = None,
    ):
        self.cluster = cluster
        self.cp = cloud_provider
        self.options = options or Options()
        self.clock = clock or cluster.clock

    def reconcile(self) -> None:
        # one trace per lifecycle pass: Launched/LaunchRetryable/
        # Registered events stamp the pass's trace id (same
        # cross-referencing contract as provisioning.pass)
        with tracing.span("lifecycle.pass"):
            for claim in self.cluster.nodeclaims.list():
                if claim.meta.deleting:
                    continue
                if not claim.is_(COND_LAUNCHED):
                    self._launch(claim)
                elif not claim.is_(COND_REGISTERED):
                    self._register(claim)
                elif not claim.is_(COND_INITIALIZED):
                    self._initialize(claim)

    # -- launch -----------------------------------------------------------
    def _launch(self, claim: NodeClaim) -> None:
        try:
            self.cp.create(claim)
            self.cluster.nodeclaims.update(claim)
            metrics.NODECLAIMS_LAUNCHED.inc(nodepool=claim.nodepool)
            self.cluster.record_event(
                "NodeClaim", claim.name, "Launched",
                f"instance {claim.provider_id}")
        except InsufficientCapacity as e:
            self.cluster.record_event(
                "NodeClaim", claim.name, "LaunchRetryable", str(e))
            # the failed attempt fed ICE pools into the unavailable-offerings
            # cache, so the next attempt sees different candidates — surface
            # that external-state progress as a cluster mutation so the
            # fixed-point manager keeps reconciling (the reference gets this
            # for free from workqueue requeues)
            self.cluster.mutated()
        except NodeClassNotReady as e:
            # waits on external readiness; nothing to retry until it changes
            self.cluster.record_event(
                "NodeClaim", claim.name, "LaunchRetryable", str(e))
        except CloudProviderError as e:
            # terminal for this claim: remove it; nominated pods re-enter the
            # provisioning queue once the nomination is cleared by the binder
            self.cluster.record_event(
                "NodeClaim", claim.name, "LaunchFailed", str(e))
            self.cluster.nodeclaims.remove_finalizer(
                claim.name, wellknown.TERMINATION_FINALIZER)
            self.cluster.nodeclaims.delete(claim.name)
        except Exception as e:  # noqa: BLE001 — raw cloud API errors
            if not errors.is_retryable(e):
                raise
            # cloud unreachable: keep the claim, retry next reconcile
            # (SURVEY §5 failure detection — launch failure must never
            # crash the control loop)
            self.cluster.record_event(
                "NodeClaim", claim.name, "LaunchRetryable", str(e))

    # -- register ---------------------------------------------------------
    def _register(self, claim: NodeClaim) -> None:
        node = self.cluster.node_for_claim(claim)
        if node is None:
            self._liveness_gc(claim)
            return
        claim.node_name = node.name
        claim.set_condition(COND_REGISTERED)
        metrics.NODECLAIMS_REGISTERED.inc(nodepool=claim.nodepool)
        node.meta.labels[wellknown.REGISTERED_LABEL] = "true"
        # strip the unregistered taint the node joined with
        node.taints = [
            t for t in node.taints
            if t.key != wellknown.UNREGISTERED_TAINT_KEY
        ]
        self.cluster.nodes.update(node)
        self.cluster.nodeclaims.update(claim)

    def _liveness_gc(self, claim: NodeClaim) -> None:
        """Never-registered claims are reclaimed after registration_ttl
        (designs/limits.md:23-25)."""
        if claim.launch_time is None:
            return
        if self.clock.now() - claim.launch_time < self.options.registration_ttl:
            return
        self.cluster.record_event(
            "NodeClaim", claim.name, "RegistrationTimeout",
            "node never joined; reclaiming instance")
        self.cp.delete(claim)
        self.cluster.nodeclaims.remove_finalizer(
            claim.name, wellknown.TERMINATION_FINALIZER)
        self.cluster.nodeclaims.delete(claim.name)

    # -- initialize -------------------------------------------------------
    def _initialize(self, claim: NodeClaim) -> None:
        node = self.cluster.node_for_claim(claim)
        if node is None or not node.ready:
            return
        # startup taints must have been removed and capacity reported
        startup_keys = {t.key for t in claim.startup_taints}
        if any(t.key in startup_keys for t in node.taints):
            return
        if node.allocatable.is_zero():
            return
        claim.set_condition(COND_INITIALIZED)
        metrics.NODECLAIMS_INITIALIZED.inc(nodepool=claim.nodepool)
        node.meta.labels[wellknown.INITIALIZED_LABEL] = "true"
        self.cluster.nodes.update(node)
        self.cluster.nodeclaims.update(claim)
