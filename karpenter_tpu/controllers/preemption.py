"""Preemption controller — executes the planner's eviction plans
(ISSUE 16).

The solver attaches :class:`PreemptionPlan`s to its result
(solver/preempt.py) and the provisioner stamps every victim pod with
the plan annotations (``karpenter.tpu/preempt-plan`` = plan id,
``karpenter.tpu/preempted-for`` = the target pods).  This controller
reconciles those annotations into evictions:

  * **atomic per plan** — if ANY victim fails its eviction gate
    (do-not-disrupt set after planning, or the pod turned out to be a
    daemonset), NO victim is evicted: the annotations are cleared, the
    plan counts ``outcome=blocked``, and the next provisioning pass
    replans against the new reality.  Gang victims are whole-gang
    inside one plan by planner construction, so plan atomicity IS gang
    atomicity.
  * **termination-style drain** — an evicted victim goes back to
    ``Pending`` with its node binding and nominations cleared, exactly
    how the termination path releases pods, so the next pass reschedules
    it at its own (lower) priority.
  * **ledger truth** — one ``source="preemption"`` record per executed
    plan with ``reason_code=PreemptedFor`` and ``cost_delta=0.0``
    (IEEE-hex-exact via the ledger's ``cost_delta_hex``): an eviction
    moves pods, never money — the fleet's nodes are untouched.

Victims that vanished before execution (completed, already rescheduled)
make the plan ``outcome=stale`` — nothing to do, annotations of any
stragglers are cleared.
"""

from __future__ import annotations

from karpenter_tpu.cluster import Cluster
from karpenter_tpu.controllers.provisioning import NOMINATED_ANNOTATION
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import Pod
from karpenter_tpu.utils import ledger, metrics


class Preemption:
    name = "preemption"

    def __init__(self, cluster: Cluster, cloud_provider=None):
        self.cluster = cluster
        # optional: only the ledger's fleet-cost snapshot needs pricing
        self.cp = cloud_provider

    def reconcile(self) -> None:
        plans: dict = {}
        for pod in self.cluster.pods.list():
            pid = pod.meta.annotations.get(
                wellknown.PREEMPT_PLAN_ANNOTATION)
            if pid:
                plans.setdefault(pid, []).append(pod)
        for pid in sorted(plans):
            self._execute(pid, plans[pid])

    @staticmethod
    def _can_evict(pod: Pod) -> bool:
        return not (pod.is_daemonset or pod.do_not_disrupt())

    def _clear(self, pod: Pod) -> None:
        pod.meta.annotations.pop(wellknown.PREEMPT_PLAN_ANNOTATION, None)
        pod.meta.annotations.pop(wellknown.PREEMPT_FOR_ANNOTATION, None)
        self.cluster.pods.update(pod)

    def _execute(self, plan_id: str, victims: list) -> None:
        from karpenter_tpu.solver import explain as explainmod
        target = victims[0].meta.annotations.get(
            wellknown.PREEMPT_FOR_ANNOTATION, "")
        live = [p for p in victims if p.node_name]
        if not live:
            for p in victims:
                self._clear(p)
            metrics.PREEMPTIONS.inc(outcome="stale")
            return
        blocked = [p for p in live if not self._can_evict(p)]
        if blocked:
            # atomic: one blocked victim voids the WHOLE plan — a
            # partial eviction would pay the disruption without freeing
            # enough capacity to seat the target
            for p in victims:
                self._clear(p)
            metrics.PREEMPTIONS.inc(outcome="blocked")
            self.cluster.record_event(
                "Pod", blocked[0].meta.name, "PreemptionBlocked",
                f"plan {plan_id}: victim {blocked[0].meta.name} is not "
                "evictable; no victim evicted")
            return
        pricing = getattr(getattr(self.cp, "instance_types", None),
                          "pricing", None)
        fleet_before = (ledger.fleet_cost(self.cluster, pricing)["total"]
                        if ledger.LEDGER.enabled else None)
        nodes = set()
        for p in live:
            nodes.add(p.node_name)
            self.cluster.record_event(
                "Pod", p.meta.name, "Preempted",
                f"plan {plan_id}: evicted for higher-priority {target}")
            p.node_name = None
            p.phase = "Pending"
            p.meta.annotations.pop(NOMINATED_ANNOTATION, None)
            self._clear(p)
        metrics.PREEMPTIONS.inc(outcome="evicted")
        if ledger.LEDGER.enabled:
            ledger.LEDGER.record(
                "preemption", "evict",
                reason_code=explainmod.PREEMPTED_FOR,
                detail=f"plan {plan_id}: {len(live)} pod(s) evicted "
                       f"from {len(nodes)} node(s) for {target}",
                nodes_delta=0, pods_affected=len(live),
                fleet_cost_before=fleet_before, cost_delta=0.0)
