"""Reconcilers (reference: sigs.k8s.io/karpenter/pkg/controllers — the core
set — plus the provider controllers of pkg/controllers)."""

from karpenter_tpu.controllers.manager import ControllerManager
from karpenter_tpu.controllers.provisioning import Provisioner
from karpenter_tpu.controllers.lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.kubelet import FakeKubelet
from karpenter_tpu.controllers.binder import PodBinder
from karpenter_tpu.controllers.termination import Termination
from karpenter_tpu.controllers.interruption import Interruption
from karpenter_tpu.controllers.preemption import Preemption
from karpenter_tpu.controllers.gc import GarbageCollection
from karpenter_tpu.controllers.expiration import Expiration
from karpenter_tpu.controllers.disruption import Disruption
from karpenter_tpu.controllers.nodeclass import (
    NodeClassHash,
    NodeClassStatus,
    NodeClassTermination,
)
from karpenter_tpu.controllers.tagging import NodeClaimTagging
from karpenter_tpu.controllers.refresh import InstanceTypeRefresh, PricingRefresh

__all__ = [
    "ControllerManager",
    "Provisioner",
    "NodeClaimLifecycle",
    "FakeKubelet",
    "PodBinder",
    "Termination",
    "Interruption",
    "Preemption",
    "GarbageCollection",
    "Expiration",
    "Disruption",
    "NodeClassHash",
    "NodeClassStatus",
    "NodeClassTermination",
    "NodeClaimTagging",
    "InstanceTypeRefresh",
    "PricingRefresh",
]
