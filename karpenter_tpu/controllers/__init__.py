"""Reconcilers (reference: sigs.k8s.io/karpenter/pkg/controllers — the core
set — plus the provider controllers of pkg/controllers)."""

from karpenter_tpu.controllers.manager import ControllerManager
from karpenter_tpu.controllers.provisioning import Provisioner
from karpenter_tpu.controllers.lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.kubelet import FakeKubelet
from karpenter_tpu.controllers.binder import PodBinder

__all__ = [
    "ControllerManager",
    "Provisioner",
    "NodeClaimLifecycle",
    "FakeKubelet",
    "PodBinder",
]
