"""Expiration — nodes past their NodePool `expireAfter` are replaced.

Reference: NodePool.spec.template.spec.expireAfter
(karpenter.sh_nodepools.yaml) — expiration deletes the claim; the
termination flow drains it and the provisioner replaces the capacity.
Each expiry writes one decision-ledger record (utils/ledger.py): the
expired node's $/hr leaves the fleet now, and the replacement capacity
shows up as a later provisioning launch record.
"""

from __future__ import annotations

from karpenter_tpu.cluster import Cluster
from karpenter_tpu.utils import ledger


class Expiration:
    name = "expiration"

    def __init__(self, cluster: Cluster, cloud_provider=None):
        self.cluster = cluster
        # optional: only needed for the ledger's pricing lookups — the
        # controller's own logic is pure clock-vs-expireAfter
        self.cp = cloud_provider
        # per-reconcile running fleet $/hr + pods-by-node index: a
        # pool-wide expireAfter sweep deletes many claims in ONE pass —
        # walk the fleet (and the pod list) once, then advance by each
        # record's own delta (interruption's drain-scoped discipline,
        # not O(expired × fleet))
        self._pass_fleet_cost = None
        self._pass_cache: dict = {}

    def reconcile(self) -> None:
        self._pass_fleet_cost = None
        self._pass_cache = {}
        now = self.cluster.clock.now()
        for claim in self.cluster.nodeclaims.list(lambda c: not c.meta.deleting):
            pool = self.cluster.nodepools.get(claim.nodepool)
            if pool is None or pool.expire_after is None:
                continue
            if claim.launch_time is None:
                continue
            if now - claim.launch_time >= pool.expire_after:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "Expired",
                    f"older than expireAfter={pool.expire_after}s")
                self._ledger_expiry(claim, pool)
                self.cluster.nodeclaims.delete(claim.name)

    def _ledger_expiry(self, claim, pool) -> None:
        from karpenter_tpu.solver import explain as explainmod
        if ledger.LEDGER.enabled and self._pass_fleet_cost is None:
            pricing = getattr(getattr(self.cp, "instance_types", None),
                              "pricing", None)
            self._pass_fleet_cost = ledger.fleet_cost(
                self.cluster, pricing)["total"]
        rec = ledger.record_claim_delete(
            self.cluster, self.cp, claim,
            source="expiration", reason_code=explainmod.NODE_EXPIRED,
            detail=f"{claim.name} older than "
                   f"expireAfter={pool.expire_after}s",
            fleet_before=self._pass_fleet_cost,
            pass_cache=self._pass_cache)
        if rec is not None:
            self._pass_fleet_cost += rec.cost_delta
