"""Expiration — nodes past their NodePool `expireAfter` are replaced.

Reference: NodePool.spec.template.spec.expireAfter
(karpenter.sh_nodepools.yaml) — expiration deletes the claim; the
termination flow drains it and the provisioner replaces the capacity.
"""

from __future__ import annotations

from karpenter_tpu.cluster import Cluster


class Expiration:
    name = "expiration"

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self) -> None:
        now = self.cluster.clock.now()
        for claim in self.cluster.nodeclaims.list(lambda c: not c.meta.deleting):
            pool = self.cluster.nodepools.get(claim.nodepool)
            if pool is None or pool.expire_after is None:
                continue
            if claim.launch_time is None:
                continue
            if now - claim.launch_time >= pool.expire_after:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "Expired",
                    f"older than expireAfter={pool.expire_after}s")
                self.cluster.nodeclaims.delete(claim.name)
