"""PodBinder — the kube-scheduler's observable role in the fake cluster.

The reference relies on the real kube-scheduler to bind pending pods once
capacity registers; here nominated pods bind to their claim's node when it
is ready, and stale nominations (claim vanished — e.g. terminal launch
failure) are cleared so pods re-enter the provisioning queue.
"""

from __future__ import annotations

from karpenter_tpu.cluster import Cluster
from karpenter_tpu.controllers.provisioning import NOMINATED_ANNOTATION
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.taints import tolerates_all


class PodBinder:
    name = "pod-binder"

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self) -> None:
        for pod in self.cluster.pods.list(lambda p: not p.scheduled):
            claim_name = pod.meta.annotations.get(NOMINATED_ANNOTATION)
            if claim_name is None:
                continue
            claim = self.cluster.nodeclaims.get(claim_name)
            if claim is None or claim.meta.deleting:
                del pod.meta.annotations[NOMINATED_ANNOTATION]
                self.cluster.pods.update(pod)
                continue
            node = self.cluster.node_for_claim(claim)
            if node is None or not node.ready or node.meta.deleting:
                continue
            if not tolerates_all(node.taints, pod.tolerations):
                continue  # startup/unregistered taints still present
            pod.node_name = node.name
            pod.phase = "Running"
            # WaitForFirstConsumer volume binding: unbound claims bind to
            # the zone the scheduler picked (scheduling.md:381-417) — from
            # here on the pod (and any future reschedule) is zone-pinned
            zone = node.labels.get(wellknown.ZONE_LABEL)
            if zone is not None:  # a zone-less node can't pin the volume;
                for claim in pod.volume_claims:  # leave the claim unbound
                    if not claim.bound:
                        claim.bound = True
                        claim.zone = zone
            del pod.meta.annotations[NOMINATED_ANNOTATION]
            self.cluster.pods.update(pod)
