"""Provisioning controller: pending pods → batch → solve → NodeClaims.

Mirrors the core provisioning controller (SURVEY §2.2/§3.2): watches
unschedulable pods, batches them (idle 1 s / max 10 s — settings.md
BATCH_*), runs the scheduler against cluster state + per-pool instance
types, creates NodeClaims for new nodes and binds pods that fit existing
capacity. The TPU solver is feature-gated with the CPU oracle as fallback —
solver failure must never fail provisioning (SURVEY §5).
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.controllers.state import GatedSolver, build_schedule_input
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import NodeClaim, ObjectMeta, Pod
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling import ScheduleInput
from karpenter_tpu.scheduling.types import NewNodeClaim, ScheduleResult
from karpenter_tpu.utils import errors, ledger, metrics, tracing
from karpenter_tpu.utils.clock import Clock

NOMINATED_ANNOTATION = "karpenter.sh/nominated-claim"


class Provisioner:
    name = "provisioning"
    # fleet-metric staleness bound when the generation is quiet (the
    # price book can change out-of-band): one O(nodes+pods+types)
    # sweep per this many seconds, worst case
    FLEET_METRICS_TTL = 30.0

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: TPUCloudProvider,
        options: Optional[Options] = None,
        clock: Optional[Clock] = None,
        solver: Optional[GatedSolver] = None,
    ):
        self.cluster = cluster
        self.cp = cloud_provider
        self.options = options or Options()
        self.clock = clock or cluster.clock
        self.solver = solver or GatedSolver(self.options, cluster)
        self._claim_seq = 0
        self._batch_first: Optional[float] = None
        self._batch_sig: Optional[frozenset] = None
        self._batch_last_change: Optional[float] = None
        # pod uid → first time seen pending, for the backlog-age gauge
        # (degraded-mode liveness: shed pods re-enter later passes, and
        # the oldest pending pod's age must shrink to zero as the
        # backlog drains — designs/limits.md:23-25 liveness discipline)
        self._first_pending: dict = {}
        self._warmup_started = False

    # -- startup warm-up (solver padding-bucket precompile) ---------------
    def _maybe_warmup(self) -> None:
        """Fire the solver's padding-bucket precompile ONCE per process,
        in a background thread, gated by KARPENTER_TPU_WARMUP (off by
        default: unit tests and tiny deployments must not pay a compile
        storm at construction; production sets it so the first real
        burst meets a fully-compiled kernel lattice — docs/solver-
        pipeline.md).  A synthetic one-pod input pins the catalog and
        existing-node buckets; the extra G-bucket shapes cover burst
        sizes up to the 50k headline class."""
        if self._warmup_started:
            return
        from karpenter_tpu.utils.knobs import env_bool
        if not env_bool("KARPENTER_TPU_WARMUP"):
            self._warmup_started = True
            return
        if not self.cluster.nodepools.list(lambda p: not p.meta.deleting):
            return  # no catalog yet — retry on a later pass
        self._warmup_started = True

        def _run():
            from karpenter_tpu.utils.logging import get_logger
            try:
                from karpenter_tpu.models.resources import Resources
                pod = Pod(meta=ObjectMeta(name="karpenter-warmup"),
                          requests=Resources.parse(
                              {"cpu": "100m", "memory": "128Mi"}))
                inp = self._build_input([pod])
                e = len(inp.existing_nodes)
                warmed = self.solver.warmup(inp, shapes=((8, e), (512, e)))
                get_logger("provisioning").info(
                    "solver warm-up complete", programs=warmed)
            except Exception as exc:  # noqa: BLE001
                get_logger("provisioning").warn(
                    "solver warm-up failed; first solves compile cold",
                    error=str(exc)[:200])

        import threading
        threading.Thread(target=_run, name="solver-warmup",
                         daemon=True).start()

    # -- batching (settings.md BATCH_IDLE/MAX_DURATION) -------------------
    def _batch_ready(self, pending: List[Pod]) -> bool:
        now = self.clock.now()
        sig = frozenset(p.meta.uid for p in pending)
        if not pending:
            self._batch_first = self._batch_sig = self._batch_last_change = None
            return False
        if sig != self._batch_sig:
            self._batch_sig = sig
            self._batch_last_change = now
            if self._batch_first is None:
                self._batch_first = now
        idle = self.options.batch_idle_duration
        maxd = self.options.batch_max_duration
        if idle <= 0:
            return True
        return (now - self._batch_last_change >= idle
                or now - self._batch_first >= maxd)

    # -- reconcile --------------------------------------------------------
    def reconcile(self) -> None:
        self._maybe_warmup()
        pending = [
            p for p in self.cluster.pending_pods()
            if NOMINATED_ANNOTATION not in p.meta.annotations
        ]
        metrics.SCHEDULING_QUEUE_DEPTH.set(len(pending))
        now = self.clock.now()
        live = {p.meta.uid for p in pending}
        for uid in live - self._first_pending.keys():
            self._first_pending[uid] = now
        for uid in list(self._first_pending):
            if uid not in live:
                del self._first_pending[uid]
        metrics.PROVISIONER_BACKLOG_AGE.set(
            max((now - t for t in self._first_pending.values()),
                default=0.0))
        # fleet spend/packing gauges (ISSUE 14): refreshed whenever the
        # cluster actually changed (generation-gated — the sweep is
        # O(nodes + pods + types) of pure Python, and an idle 1 s
        # reconcile tick must not pay it to recompute identical
        # values), plus a TTL fallback: the price book can move WITHOUT
        # a store mutation (PricingRefresh updates the provider, never
        # the generation), and an idle fleet must not export stale $/hr
        # forever.  Best-effort — a pricing/discovery hiccup degrades
        # the gauges, never the loop
        gen = self.cluster.generation
        last = getattr(self, "_fleet_metrics_at", None)
        if (gen != getattr(self, "_fleet_metrics_gen", None)
                or last is None
                or now - last >= self.FLEET_METRICS_TTL):
            try:
                ledger.update_fleet_metrics(self.cluster, self.cp)
                self._fleet_metrics_gen = gen
                self._fleet_metrics_at = now
            except Exception as e:  # noqa: BLE001 — advisory telemetry
                from karpenter_tpu.utils.logging import get_logger
                get_logger(self.name).warn(
                    "fleet cost metrics refresh failed",
                    error=str(e)[:200])
        if not self._batch_ready(pending):
            return
        self._batch_first = self._batch_sig = self._batch_last_change = None

        # ONE trace per provisioning pass, rooted here: every child span
        # (input assembly, solve phases, remote-solver RPC, store I/O,
        # apply) hangs off this id, and record_event stamps it so events
        # and traces cross-reference
        with tracing.span("provisioning.pass", pods=len(pending)) as _sp:
            try:
                with tracing.span("provisioning.build_input"):
                    inp = self._build_input(pending)
            except Exception as e:  # noqa: BLE001
                # catalog discovery hit a cloud outage with a cold cache —
                # keep the pods pending and retry next round (provisioning
                # must never crash the loop, SURVEY §5)
                if not errors.is_retryable(e):
                    raise
                self.cluster.record_event(
                    "Provisioner", "provisioning", "SchedulingRetryable",
                    str(e))
                return
            with metrics.SCHEDULING_DURATION.time():
                with tracing.span("provisioning.solve"):
                    result = self._solve(inp)
            with tracing.span("provisioning.apply"):
                self._apply(result, pending)
            if _sp is not None:
                _sp.attrs["new_claims"] = len(result.new_claims)
                _sp.attrs["unschedulable"] = len(result.unschedulable)

    # -- input assembly ---------------------------------------------------
    def _build_input(self, pending: List[Pod]) -> ScheduleInput:
        return build_schedule_input(self.cluster, self.cp, pending)

    def _solve(self, inp: ScheduleInput) -> ScheduleResult:
        return self.solver.solve(inp, source="provisioning")

    # -- apply -------------------------------------------------------------
    def _apply(self, result: ScheduleResult,
               pods: "List[Pod] | None" = None) -> None:
        if pods:
            # gang placement outcomes (ISSUE 15): ONE increment per gang
            # per pass.  By the atomicity invariant a gang is either
            # fully placed or fully stranded — outcome is derived from
            # "any member unschedulable", and a partial gang would show
            # up on the solver's gang-repair counter, never here.
            from karpenter_tpu.scheduling.types import gang_of
            gangs: dict = {}
            for p in pods:
                sp = gang_of(p)
                if sp is not None:
                    placed = p.meta.name not in result.unschedulable
                    gangs[sp.name] = gangs.get(sp.name, True) and placed
            for _name, placed in sorted(gangs.items()):
                metrics.GANG_PLACEMENTS.inc(
                    outcome="placed" if placed else "stranded")
        for pod_name, node_name in result.existing_assignments.items():
            pod = self.cluster.pods.get(pod_name)
            node = self.cluster.nodes.get(node_name)
            if pod is None or node is None:
                continue
            pod.node_name = node_name
            pod.phase = "Running"
            self.cluster.pods.update(pod)

        for claim_spec in result.new_claims:
            claim = self._create_claim(claim_spec)
            for pod in claim_spec.pods:
                live = self.cluster.pods.get(pod.meta.name)
                if live is not None:
                    live.meta.annotations[NOMINATED_ANNOTATION] = claim.name
                    self.cluster.pods.update(live)
        if result.new_claims and ledger.LEDGER.enabled:
            # decision ledger (ISSUE 14): one launch record per pass —
            # cost delta is the exact sum of the planned claims' prices
            # (the same floats the solver minimized), fleet-before is the
            # independent sum over live nodes
            from karpenter_tpu.solver import explain as explainmod
            pricing = getattr(self.cp.instance_types, "pricing", None)
            ledger.LEDGER.record(
                "provisioning", "launch",
                reason_code=explainmod.CAPACITY_LAUNCHED,
                detail=f"{len(result.new_claims)} claim(s) for "
                       f"{sum(len(s.pods) for s in result.new_claims)} "
                       "pod(s)",
                pools=[s.nodepool for s in result.new_claims],
                nodes_delta=len(result.new_claims),
                pods_affected=sum(len(s.pods) for s in result.new_claims)
                + len(result.existing_assignments),
                fleet_cost_before=ledger.fleet_cost(
                    self.cluster, pricing)["total"],
                cost_delta=sum(s.price for s in result.new_claims))

        if result.preemptions:
            # preemption plans (ISSUE 16): stamp every victim with the
            # plan annotations — the Preemption controller executes the
            # evictions atomically per plan; the provisioner only
            # publishes the decision
            for plan in result.preemptions:
                target = ",".join(plan.target_pods)
                stamped = 0
                for vname in plan.victim_pod_names():
                    live = self.cluster.pods.get(vname)
                    if live is None or not live.node_name:
                        continue
                    live.meta.annotations[
                        wellknown.PREEMPT_PLAN_ANNOTATION] = plan.plan_id
                    live.meta.annotations[
                        wellknown.PREEMPT_FOR_ANNOTATION] = target
                    self.cluster.pods.update(live)
                    stamped += 1
                if stamped:
                    self.cluster.record_event(
                        "Pod", plan.target_pods[0], "PreemptionPlanned",
                        f"plan {plan.plan_id}: evict {stamped} "
                        f"lower-priority pod(s) to seat {target}")

        if result.unschedulable:
            # placement provenance (ISSUE 13): this is the authoritative
            # "pod is unschedulable" surface — every solver path (device,
            # split, rescue, degraded, remote: the reason tree rides the
            # pickled Reason) lands here, so the per-reason counter and
            # the explain store are fed here, not inside the solver
            # (whose solve() also serves counterfactual simulations)
            from karpenter_tpu.solver import explain as explainmod
            explainmod.STORE.register(
                result.unschedulable,
                trace_id=tracing.current_trace_id(),
                source="provisioning")
            for reason in result.unschedulable.values():
                metrics.UNSCHEDULABLE_PODS.inc(
                    reason=explainmod.code_of(reason))
        for pod_name, reason in result.unschedulable.items():
            self.cluster.record_event(
                "Pod", pod_name, "FailedScheduling", reason)

    def _create_claim(self, spec: NewNodeClaim) -> NodeClaim:
        # generateName semantics: the sequence keeps names readable and
        # roughly ordered, but uniqueness must hold across REPLICAS — two
        # operators each start their counter at zero, and a failover's
        # dual-writer window would collide on bare sequence names (k8s
        # solves this with a random generateName suffix)
        self._claim_seq += 1
        name = f"{spec.nodepool}-{self._claim_seq}"
        import uuid
        if name in self.cluster.nodeclaims:
            name = f"{spec.nodepool}-{uuid.uuid4().hex[:8]}"
        try:
            return create_claim_from_spec(self.cluster, self.cp, spec, name)
        except ValueError:
            # the authoritative store held the name even though our cache
            # didn't (peer's create not yet synced): retry under a random
            # name — the window where this recurses twice is negligible
            return create_claim_from_spec(
                self.cluster, self.cp, spec,
                f"{spec.nodepool}-{uuid.uuid4().hex[:8]}")


def create_claim_from_spec(cluster: Cluster, cp: TPUCloudProvider,
                           spec: NewNodeClaim, name: str) -> NodeClaim:
    """NewNodeClaim (scheduler output) → NodeClaim CR, shared by the
    provisioner and the disruption controller's replacement pre-spin."""
    pool = cluster.nodepools.get(spec.nodepool)
    nc = cp.node_classes.get(spec.node_class_ref)
    claim = NodeClaim(
        meta=ObjectMeta(
            name=name,
            labels={wellknown.NODEPOOL_LABEL: spec.nodepool},
            annotations={
                wellknown.NODEPOOL_HASH_ANNOTATION:
                    pool.static_hash() if pool else "",
                wellknown.NODECLASS_HASH_ANNOTATION:
                    nc.static_hash() if nc else "",
            },
            finalizers=[wellknown.TERMINATION_FINALIZER],
        ),
        nodepool=spec.nodepool,
        nodepool_uid=(pool.meta.uid if pool else None),
        node_class_ref=spec.node_class_ref,
        requirements=spec.requirements.copy(),
        resource_requests=spec.requests.copy(),
        taints=list(spec.taints),
        startup_taints=list(spec.startup_taints),
        instance_type_options=list(spec.instance_type_names),
        termination_grace_period=(
            pool.termination_grace_period if pool else None),
    )
    cluster.nodeclaims.create(claim)
    return claim
