"""Provisioning controller: pending pods → batch → solve → NodeClaims.

Mirrors the core provisioning controller (SURVEY §2.2/§3.2): watches
unschedulable pods, batches them (idle 1 s / max 10 s — settings.md
BATCH_*), runs the scheduler against cluster state + per-pool instance
types, creates NodeClaims for new nodes and binds pods that fit existing
capacity. The TPU solver is feature-gated with the CPU oracle as fallback —
solver failure must never fail provisioning (SURVEY §5).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import NodeClaim, NodePool, ObjectMeta, Pod
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.models.taints import tolerates_all
from karpenter_tpu.operator.options import Options
from karpenter_tpu.scheduling import ExistingNode, ScheduleInput, Scheduler
from karpenter_tpu.scheduling.types import (
    NewNodeClaim,
    ScheduleResult,
    effective_request,
)
from karpenter_tpu.solver import TPUSolver, UnsupportedPods
from karpenter_tpu.utils.clock import Clock

NOMINATED_ANNOTATION = "karpenter.sh/nominated-claim"


class Provisioner:
    name = "provisioning"

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: TPUCloudProvider,
        options: Optional[Options] = None,
        clock: Optional[Clock] = None,
    ):
        self.cluster = cluster
        self.cp = cloud_provider
        self.options = options or Options()
        self.clock = clock or cluster.clock
        self.tpu_solver = TPUSolver(max_nodes=self.options.solver_max_nodes)
        self._claim_seq = 0
        self._batch_first: Optional[float] = None
        self._batch_sig: Optional[frozenset] = None
        self._batch_last_change: Optional[float] = None

    # -- batching (settings.md BATCH_IDLE/MAX_DURATION) -------------------
    def _batch_ready(self, pending: List[Pod]) -> bool:
        now = self.clock.now()
        sig = frozenset(p.meta.uid for p in pending)
        if not pending:
            self._batch_first = self._batch_sig = self._batch_last_change = None
            return False
        if sig != self._batch_sig:
            self._batch_sig = sig
            self._batch_last_change = now
            if self._batch_first is None:
                self._batch_first = now
        idle = self.options.batch_idle_duration
        maxd = self.options.batch_max_duration
        if idle <= 0:
            return True
        return (now - self._batch_last_change >= idle
                or now - self._batch_first >= maxd)

    # -- reconcile --------------------------------------------------------
    def reconcile(self) -> None:
        pending = [
            p for p in self.cluster.pending_pods()
            if NOMINATED_ANNOTATION not in p.meta.annotations
        ]
        if not self._batch_ready(pending):
            return
        self._batch_first = self._batch_sig = self._batch_last_change = None

        inp = self._build_input(pending)
        result = self._solve(inp)
        self._apply(result)

    # -- input assembly ---------------------------------------------------
    def _build_input(self, pending: List[Pod]) -> ScheduleInput:
        pools: List[NodePool] = self.cluster.nodepools.list(
            lambda np_: not np_.meta.deleting)
        instance_types = {
            p.name: self.cp.get_instance_types(p.node_class_ref) for p in pools
        }

        existing: List[ExistingNode] = []
        for node in self.cluster.nodes.list(lambda n: not n.meta.deleting):
            resident = self.cluster.pods_on_node(node.name)
            used = Resources()
            for pod in resident:
                used += effective_request(pod)
            existing.append(ExistingNode(
                node=node, available=node.allocatable - used, pods=resident))

        daemon_overhead = {
            p.name: self._daemon_overhead(p) for p in pools
        }
        remaining_limits = {
            p.name: self._remaining_limit(p) for p in pools
        }
        return ScheduleInput(
            pods=pending,
            nodepools=pools,
            instance_types=instance_types,
            existing_nodes=existing,
            daemon_overhead=daemon_overhead,
            remaining_limits=remaining_limits,
        )

    def _daemon_overhead(self, pool: NodePool) -> Resources:
        """Aggregate requests of daemonset pods a new node in this pool
        would run (daemonset overhead accounting — SURVEY §2.2 scheduler)."""
        template = pool.template_requirements()
        total = Resources()
        for pod in self.cluster.daemonset_pods():
            if not tolerates_all(pool.taints, pod.tolerations):
                continue
            if not template.compatible(pod.requirements):
                continue
            total += effective_request(pod)
        return total

    def _remaining_limit(self, pool: NodePool) -> Optional[Resources]:
        if pool.limits is None:
            return None
        used = Resources()
        for claim in self.cluster.nodeclaims.list(
                lambda c: c.nodepool == pool.name):
            # unlaunched claims have no capacity yet — charge their planned
            # requests so stalled launches still hold their limit reservation
            used += (claim.capacity if not claim.capacity.is_zero()
                     else claim.resource_requests)
        remaining = pool.limits - used
        return remaining

    # -- solve (gated, with fallback) -------------------------------------
    def _solve(self, inp: ScheduleInput) -> ScheduleResult:
        if self.options.feature_gates.tpu_solver:
            try:
                return self.tpu_solver.solve(inp)
            except UnsupportedPods:
                pass  # constraints the encoder can't express yet → oracle
            except Exception as e:  # noqa: BLE001 — solver down ⇒ fall back
                self.cluster.record_event(
                    "Provisioner", "solver", "SolverFallback", str(e))
        return Scheduler(inp).solve()

    # -- apply -------------------------------------------------------------
    def _apply(self, result: ScheduleResult) -> None:
        for pod_name, node_name in result.existing_assignments.items():
            pod = self.cluster.pods.get(pod_name)
            node = self.cluster.nodes.get(node_name)
            if pod is None or node is None:
                continue
            pod.node_name = node_name
            pod.phase = "Running"
            self.cluster.pods.update(pod)

        for claim_spec in result.new_claims:
            claim = self._create_claim(claim_spec)
            for pod in claim_spec.pods:
                live = self.cluster.pods.get(pod.meta.name)
                if live is not None:
                    live.meta.annotations[NOMINATED_ANNOTATION] = claim.name
                    self.cluster.pods.update(live)

        for pod_name, reason in result.unschedulable.items():
            self.cluster.record_event(
                "Pod", pod_name, "FailedScheduling", reason)

    def _create_claim(self, spec: NewNodeClaim) -> NodeClaim:
        self._claim_seq += 1
        pool = self.cluster.nodepools.get(spec.nodepool)
        nc = self.cp.node_classes.get(spec.node_class_ref)
        name = f"{spec.nodepool}-{self._claim_seq}"
        claim = NodeClaim(
            meta=ObjectMeta(
                name=name,
                labels={wellknown.NODEPOOL_LABEL: spec.nodepool},
                annotations={
                    wellknown.NODEPOOL_HASH_ANNOTATION:
                        pool.static_hash() if pool else "",
                    wellknown.NODECLASS_HASH_ANNOTATION:
                        nc.static_hash() if nc else "",
                },
                finalizers=[wellknown.TERMINATION_FINALIZER],
            ),
            nodepool=spec.nodepool,
            node_class_ref=spec.node_class_ref,
            requirements=spec.requirements.copy(),
            resource_requests=spec.requests.copy(),
            taints=list(spec.taints),
            startup_taints=list(spec.startup_taints),
            instance_type_options=list(spec.instance_type_names),
        )
        self.cluster.nodeclaims.create(claim)
        return claim
