"""NodeClass controllers — hash, status (discovery → readiness), termination.

Mirrors pkg/controllers/nodeclass:
  hash        stamps the nodeclass-hash annotation used for drift detection,
              with hash-version migration (hash/controller.go:48-128)
  status      reconciles discovered subnets / security groups / images and
              the instance profile into NodeClass.status and derives the
              Ready condition — Create() refuses non-Ready nodeclasses
              (status/{controller,subnet,securitygroup,ami,instanceprofile,
              readiness}.go; pkg/cloudprovider/cloudprovider.go:99-102)
  termination finalizer: on NodeClass delete, blocks while NodeClaims still
              reference it, then deletes instance profiles + launch
              templates and strips the finalizer
              (termination/controller.go:137)
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.cluster import Cluster
from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import NodeClass
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

NODECLASS_FINALIZER = "karpenter.tpu/termination"
HASH_VERSION = "v1"

COND_SUBNETS_READY = "SubnetsReady"
COND_SECURITY_GROUPS_READY = "SecurityGroupsReady"
COND_IMAGES_READY = "ImagesReady"
COND_INSTANCE_PROFILE_READY = "InstanceProfileReady"
COND_READY = "Ready"


class NodeClassHash:
    name = "nodeclass-hash"

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self) -> None:
        for nc in self.cluster.nodeclasses.list():
            if nc.meta.deleting:
                continue
            want = nc.static_hash()
            ann = nc.meta.annotations
            changed = False
            # hash-version migration: when the hash algorithm version bumps,
            # re-stamp instead of reporting spurious drift
            # (hash/controller.go:48-128)
            if ann.get(wellknown.NODECLASS_HASH_VERSION_ANNOTATION) \
                    != HASH_VERSION:
                ann[wellknown.NODECLASS_HASH_VERSION_ANNOTATION] = HASH_VERSION
                changed = True
            if ann.get(wellknown.NODECLASS_HASH_ANNOTATION) != want:
                ann[wellknown.NODECLASS_HASH_ANNOTATION] = want
                changed = True
            if changed:
                self.cluster.nodeclasses.update(nc)


class NodeClassStatus:
    name = "nodeclass-status"

    def __init__(self, cluster: Cluster, subnets, security_groups, images,
                 instance_profiles):
        self.cluster = cluster
        self.subnets = subnets
        self.security_groups = security_groups
        self.images = images
        self.instance_profiles = instance_profiles

    def reconcile(self) -> None:
        for nc in self.cluster.nodeclasses.list():
            if nc.meta.deleting:
                continue
            self._reconcile_one(nc)

    def _reconcile_one(self, nc: NodeClass) -> None:
        subnets = self._safe(lambda: self.subnets.list(nc)) or []
        sgs = self._safe(lambda: self.security_groups.list(nc)) or []
        images = self._safe(lambda: self.images.list(nc)) or []
        profile = self._safe(lambda: self.instance_profiles.create(nc)) or ""

        conds = {
            COND_SUBNETS_READY: bool(subnets),
            COND_SECURITY_GROUPS_READY: bool(sgs),
            COND_IMAGES_READY: bool(images),
            COND_INSTANCE_PROFILE_READY: bool(profile),
        }
        conds[COND_READY] = all(conds.values())

        status = (
            sorted(s.subnet_id for s in subnets),
            sorted(g.group_id for g in sgs),
            [i.image_id for i in images],
            sorted({s.zone for s in subnets}),
            profile,
            conds,
        )
        current = (nc.discovered_subnets, nc.discovered_security_groups,
                   nc.discovered_images, nc.discovered_zones,
                   nc.instance_profile, nc.status_conditions)
        if status == current and nc.ready == conds[COND_READY] \
                and NODECLASS_FINALIZER in nc.meta.finalizers:
            return
        was_ready = nc.ready
        (nc.discovered_subnets, nc.discovered_security_groups,
         nc.discovered_images, nc.discovered_zones,
         nc.instance_profile, nc.status_conditions) = status
        nc.ready = conds[COND_READY]
        if NODECLASS_FINALIZER not in nc.meta.finalizers:
            nc.meta.finalizers.append(NODECLASS_FINALIZER)
        if nc.ready != was_ready:
            self.cluster.record_event(
                "NodeClass", nc.name,
                "Ready" if nc.ready else "NotReady",
                ", ".join(k for k, v in conds.items() if not v))
        self.cluster.nodeclasses.update(nc)

    def _safe(self, fn):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — discovery failure ⇒ not
            # ready; recorded, not silent (kt-lint exception-hygiene): a
            # nodeclass stuck NotReady must be attributable to the
            # discovery call that keeps failing
            get_logger(self.name).warn(
                "nodeclass discovery call failed", error=str(e)[:200])
            metrics.RECONCILE_ERRORS.inc(controller=self.name)
            return None


class NodeClassTermination:
    name = "nodeclass-termination"

    def __init__(self, cluster: Cluster, launch_templates, instance_profiles,
                 instance_types=None):
        self.cluster = cluster
        self.launch_templates = launch_templates
        self.instance_profiles = instance_profiles
        self.instance_types = instance_types

    def reconcile(self) -> None:
        for nc in self.cluster.nodeclasses.list():
            if not nc.meta.deleting:
                continue
            # block while NodeClaims still reference this nodeclass — their
            # instances depend on its launch config
            refs = self.cluster.nodeclaims.list(
                lambda c: c.node_class_ref == nc.name)
            if refs:
                self.cluster.record_event(
                    "NodeClass", nc.name, "TerminationBlocked",
                    f"{len(refs)} nodeclaims still reference it")
                continue
            self.launch_templates.delete_all(nc)
            self.instance_profiles.delete(nc)
            if self.instance_types is not None:
                # drop the view's catalog gauge series (series another
                # nodeclass still exports survive)
                self.instance_types.forget(nc.name)
            self.cluster.record_event("NodeClass", nc.name, "Terminated", "")
            self.cluster.nodeclasses.remove_finalizer(
                nc.name, NODECLASS_FINALIZER)
