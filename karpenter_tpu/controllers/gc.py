"""Garbage collection.

Two directions, mirroring pkg/controllers/nodeclaim/garbagecollection
(:54-109) and the core's cloud-side reconciliation:
  * leaked instances — cloud instances tagged to this cluster with no
    matching NodeClaim are terminated (cloud-side orphans)
  * vanished instances — claims whose instance is gone (out-of-band
    termination, spot reclaim executed) are deleted so their pods
    reschedule; orphan Node objects without claims are removed
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider import TPUCloudProvider
from karpenter_tpu.cluster import Cluster
from karpenter_tpu.providers.fake_cloud import INSTANCE_RUNNING
from karpenter_tpu.utils import errors, metrics, tracing
from karpenter_tpu.utils.logging import get_logger


class GarbageCollection:
    name = "garbagecollection"

    def __init__(self, cluster: Cluster, cloud_provider: TPUCloudProvider):
        self.cluster = cluster
        self.cp = cloud_provider

    def reconcile(self) -> None:
        try:
            # one trace per sweep: record_event stamps the active trace
            # id, so reclaim/orphan events cross-reference their pass
            # exactly like provisioning's FailedScheduling events do
            with tracing.span("gc.pass"):
                self._reconcile()
        except Exception as e:  # noqa: BLE001
            # GC is cloud-read-heavy; a transient outage just means this
            # sweep is skipped (pkg/errors taxonomy — retry next round).
            # Skipped-but-visible: a silent swallow hides a persistent
            # outage (kt-lint exception-hygiene)
            if not errors.is_retryable(e):
                raise
            get_logger(self.name).warn(
                "gc sweep skipped on retryable error", error=str(e)[:200])
            metrics.RECONCILE_ERRORS.inc(controller=self.name)

    def _reconcile(self) -> None:
        claims = self.cluster.nodeclaims.list()
        by_provider = {c.provider_id for c in claims if c.provider_id}

        # leaked: instance exists, claim doesn't
        for inst in self.cp.list_instances():
            if inst.state != INSTANCE_RUNNING:
                continue
            if inst.instance_id not in by_provider:
                self.cp.cloud.terminate_instances([inst.instance_id])
                self.cluster.record_event(
                    "Instance", inst.instance_id, "LeakedInstanceReclaimed",
                    "no NodeClaim references this instance")

        # owner cascade: the reference deletes a NodePool's nodes with it
        # (owner references on NodeClaims; nodepools.md — deleting a
        # NodePool drains its nodes gracefully). Ownership is keyed on the
        # pool UID like a k8s ownerReference (ADVICE r3: name-keying
        # conflated 'pool deleted' with 'pool recreated under the same
        # name between GC passes' and drained the recreated fleet). A
        # claim whose owner UID matches no live pool is deleted here,
        # which routes through the termination controller's finalizer
        # drain, not a hard kill.
        live = self.cluster.nodepools.list(lambda p: not p.meta.deleting)
        live_uids = {p.meta.uid for p in live}
        live_names = {p.name for p in live}
        for claim in claims:
            if claim.meta.deleting:
                continue
            if claim.nodepool_uid is not None:
                orphaned = claim.nodepool_uid not in live_uids
            else:
                # claims predating UID stamping (adopted via relist): the
                # name check is the only ownership signal available
                orphaned = claim.nodepool not in live_names
            if orphaned:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "OwnerDeleted",
                    f"nodepool {claim.nodepool} was deleted; draining")
                self.cluster.nodeclaims.delete(claim.name)

        # vanished: claim exists, instance doesn't (or is terminated)
        for claim in claims:
            if not claim.provider_id or claim.meta.deleting:
                continue
            inst = self.cp.get(claim.provider_id)
            if inst is None or inst.state != INSTANCE_RUNNING:
                self.cluster.record_event(
                    "NodeClaim", claim.name, "InstanceTerminated",
                    "backing instance is gone; removing claim")
                self.cluster.nodeclaims.delete(claim.name)

        # orphan nodes: node object with no claim — unbind residents (their
        # machine is gone) so they re-enter the provisioning queue
        for node in self.cluster.nodes.list(lambda n: not n.meta.deleting):
            if self.cluster.claim_for_node(node) is None:
                for pod in self.cluster.pods_on_node(node.name):
                    if pod.is_daemonset:
                        continue
                    pod.node_name = None
                    pod.phase = "Pending"
                    self.cluster.pods.update(pod)
                self.cluster.nodes.delete(node.name)
