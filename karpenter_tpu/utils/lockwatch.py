"""Dynamic lock-order observer (ISSUE 12): the runtime half of
kt-lint's static `lock-order` analysis.

The static pass (hack/analyze/rules/lock_order.py) builds an
interprocedural lock-acquisition graph over `karpenter_tpu/` and flags
order inversions.  A static graph nobody validates is a diagram, not a
gate — this module records the acquisition edges that REALLY happen and
fails when an observed edge contradicts the static order (or when the
run itself exhibits both directions of a pair).  tests/conftest.py arms
it for the whole suite under ``KARPENTER_TPU_LOCK_OBSERVER=1``, so
tier-1 doubles as the graph's validation run.

Mechanism: :func:`install` replaces ``threading.Lock`` / ``RLock`` /
``Condition`` with factories.  A lock constructed from a frame inside
``karpenter_tpu/`` comes back wrapped (its construction site —
``karpenter_tpu/<file>.py:<line>`` — is its identity, matching the
static model's definition sites); every other caller (stdlib, jax,
tests) gets the raw primitive, so the probe costs nothing outside the
code under study.  Each observed acquire records one directed edge per
lock currently held by the acquiring thread.  ``Condition.wait``
releases and re-acquires through the wrapped lock, so held-sets stay
truthful across waits.

Edges are aggregated by construction *site*, not instance: two
instances sharing a site (every `Counter._lock`) produce self-pairs,
which are reported informationally but never failed — instance-level
ordering within one class is out of the static model's scope too.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.utils.knobs import env_bool

ENV_GATE = "KARPENTER_TPU_LOCK_OBSERVER"

# raw primitives captured at import, before any install() — the
# observer's own bookkeeping must never route through the observer
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock
_RAW_CONDITION = threading.Condition

_meta = _RAW_LOCK()                       # guards _EDGES/_installed
_tls = threading.local()                  # .held: List[(site, id(obj))]
# (site_held, site_acquired) -> first-witness thread name
_EDGES: Dict[Tuple[str, str], str] = {}
_installed = False


def armed_from_env() -> bool:
    """The opt-in gate tests/conftest.py consults before importing the
    rest of the tree."""
    return env_bool(ENV_GATE)


def _held() -> List[Tuple[str, int]]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _record_acquire(site: str, obj_id: int) -> None:
    held = _held()
    if held:
        name = threading.current_thread().name
        for h_site, h_id in held:
            key = (h_site, site)
            if key not in _EDGES:
                with _meta:
                    _EDGES.setdefault(key, name)
    held.append((site, obj_id))


def _record_release(site: str, obj_id: int) -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i] == (site, obj_id):
            del held[i]
            return


class _ObservedLock:
    """Proxy over a raw lock that reports acquisition edges.  Exposes
    exactly the subset `threading.Condition`'s fallbacks use
    (acquire/release/locked + context manager), so it slots in as a
    Condition's underlying lock unchanged."""

    __slots__ = ("_inner", "_site", "_reentrant", "_count")

    def __init__(self, inner, site: str, reentrant: bool = False):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant
        self._count = 0  # RLock: record the edge once per outermost hold

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            if self._reentrant and self._count > 0:
                self._count += 1
            else:
                self._count += 1
                _record_acquire(self._site, id(self))
        return got

    def release(self) -> None:
        if self._count > 0:
            self._count -= 1
            if self._count == 0 or not self._reentrant:
                _record_release(self._site, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition protocol (reentrant inners only) -----------------------
    # threading.Condition binds `_release_save`/`_acquire_restore`/
    # `_is_owned` off the lock IF PRESENT, else falls back to
    # release()/acquire() — correct for a plain Lock proxy (and it
    # routes through our bookkeeping), but WRONG for a wrapped RLock:
    # the fallback `_is_owned` does acquire(False), which succeeds for
    # the owning thread of a reentrant lock, so wait()/notify() would
    # raise "cannot wait on un-acquired lock", and the fallback release
    # drops only one level of a recursive hold.  Expose the protocol
    # via __getattr__ so a plain-Lock proxy still raises AttributeError
    # (keeping the tested fallback path) while an RLock proxy forwards
    # with held-set bookkeeping kept truthful across the wait.
    def __getattr__(self, name: str):
        if self._reentrant:
            if name == "_release_save":
                return self._reentrant_release_save
            if name == "_acquire_restore":
                return self._reentrant_acquire_restore
            if name == "_is_owned":
                return self._inner._is_owned
        raise AttributeError(name)

    def _reentrant_release_save(self):
        state = self._inner._release_save()
        depth = self._count
        self._count = 0
        if depth:
            _record_release(self._site, id(self))
        return (state, depth)

    def _reentrant_acquire_restore(self, saved) -> None:
        state, depth = saved
        self._inner._acquire_restore(state)
        self._count = depth
        if depth:
            _record_acquire(self._site, id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<ObservedLock {self._site} {self._inner!r}>"


def _creation_site() -> Optional[str]:
    """`karpenter_tpu/<path>:<line>` of the frame constructing the lock,
    or None when the construction is outside the package (unobserved).
    A construction from inside `threading.py` itself (the inner lock of
    an Event/Timer/Barrier) is deliberately unobserved: those are not
    lock *definitions* in the static model, and instrumenting every
    pending-response Event would tax the hot paths for edges the model
    can't anchor."""
    f = sys._getframe(2)
    if f is None:
        return None
    fn = f.f_code.co_filename.replace(os.sep, "/")
    if os.path.basename(fn) in ("threading.py", "lockwatch.py"):
        return None
    marker = "/karpenter_tpu/"
    i = fn.rfind(marker)
    if i < 0:
        return None
    return f"karpenter_tpu/{fn[i + len(marker):]}:{f.f_lineno}"


def _lock_factory():
    site = _creation_site()
    if site is None:
        return _RAW_LOCK()
    return _ObservedLock(_RAW_LOCK(), site)


def _rlock_factory():
    site = _creation_site()
    if site is None:
        return _RAW_RLOCK()
    return _ObservedLock(_RAW_RLOCK(), site, reentrant=True)


def _condition_factory(lock=None):
    # a Condition's acquisition identity IS its underlying lock's: pass
    # an observed lock through (aliasing — the static model does the
    # same for `Condition(self._lock)`), mint one for a bare Condition()
    if lock is None:
        site = _creation_site()
        lock = _ObservedLock(_RAW_LOCK(), site) if site else _RAW_LOCK()
    return _RAW_CONDITION(lock)


def install() -> None:
    """Patch the `threading` factories.  Idempotent.  Must run before
    the modules under study construct their locks (conftest arms it
    before importing jax or karpenter_tpu)."""
    global _installed
    with _meta:
        if _installed:
            return
        _installed = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory


def uninstall() -> None:
    global _installed
    with _meta:
        if not _installed:
            return
        _installed = False
    threading.Lock = _RAW_LOCK
    threading.RLock = _RAW_RLOCK
    threading.Condition = _RAW_CONDITION


def installed() -> bool:
    return _installed


def reset() -> None:
    with _meta:
        _EDGES.clear()


def edges() -> Dict[Tuple[str, str], str]:
    with _meta:
        return dict(_EDGES)


def verify(static_edges=None, site_to_id=None) -> dict:
    """Check the observed edges for inversions.

    * **dynamic inversion** — both (A,B) and (B,A) were observed in this
      run with A≠B: a textbook order inversion witnessed live.
    * **contradicts static** — the static graph orders A before B
      (edge A→B, no B→A), and this run observed B held while acquiring
      A: exactly the edge the static analysis calls inverted.

    `static_edges` is a set of (lock_id, lock_id); `site_to_id` maps
    construction sites (`path:line`) to the static model's lock ids
    (both from hack.analyze.rules.lock_order.build_model).  Same-site
    pairs are reported under `self_pairs`, never failed.  Returns
    {"inversions": [...], "self_pairs": [...], "edges": n,
    "unmodeled": n}.
    """
    snap = edges()
    inversions: List[dict] = []
    self_pairs: List[dict] = []
    unmodeled = 0
    for (a, b), thread in sorted(snap.items()):
        if a == b:
            self_pairs.append({"site": a, "thread": thread})
            continue
        if (b, a) in snap and a < b:
            inversions.append({
                "kind": "dynamic-inversion", "pair": (a, b),
                "detail": f"observed {a} -> {b} (thread {thread}) AND "
                          f"{b} -> {a} (thread {snap[(b, a)]})"})
    if static_edges is not None and site_to_id is not None:
        for (a, b), thread in sorted(snap.items()):
            ida, idb = site_to_id.get(a), site_to_id.get(b)
            if ida is None or idb is None:
                unmodeled += 1
                continue
            if ida == idb:
                continue
            if (idb, ida) in static_edges and (ida, idb) not in static_edges:
                inversions.append({
                    "kind": "contradicts-static", "pair": (a, b),
                    "detail": f"observed {ida} ({a}) held while acquiring "
                              f"{idb} ({b}) in thread {thread}, but the "
                              f"static graph orders {idb} before {ida}"})
    return {"inversions": inversions, "self_pairs": self_pairs,
            "edges": len(snap), "unmodeled": unmodeled}
