"""Repo-native fault-injection harness (ISSUE 7 tentpole part 3).

Named injection points sit at the seams where production breaks: the
solver-service RPC framing boundary, store I/O, and device dispatch.
Each point is a `faults.fire("<point>")` call at the site; disarmed (the
default, and the only state tier-1 is allowed to run in — enforced by
tests/conftest.py) it costs one module-global bool check.

Arming:

  * environment —
    `KARPENTER_TPU_FAULTS="point=mode[:arg][:times][:after],..."`
    read once at import and re-readable via `load_env()`:

        KARPENTER_TPU_FAULTS="service.client.send=delay:0.2"
        KARPENTER_TPU_FAULTS="solverd.handle_batch=crash::1,store.remote.rpc=drop"

  * programmatic — `faults.arm(point, mode, arg=..., times=...)`,
    `faults.disarm()` to clear (tests use this; an autouse fixture in
    conftest disarms after every test so one forgotten cleanup cannot
    poison the suite).

Modes (what a site does with the verdict):

  * ``delay``    — sleep ``arg`` seconds at the site, then proceed
  * ``drop``     — raise :class:`FaultInjected`; sites translate this to
                   their native failure (a dropped frame, a failed RPC)
  * ``truncate`` — for sites that pass bytes through :func:`fire`,
                   return only the first ``arg`` bytes (default: half)
                   and raise on the NEXT fire so the stream dies mid-
                   frame — the truncated-frame / mid-frame-EOF shape
  * ``crash``    — ``os._exit(arg or 137)``: sudden process death, the
                   worker-killed-mid-batch shape (only meaningful inside
                   a disposable worker process, e.g. kt_solverd's
                   backend; never arm it in the operator)
  * ``error``    — raise :class:`FaultInjected` (alias of drop for sites
                   where "drop" reads wrong, e.g. device dispatch)

``times`` bounds how often a spec fires (default: forever). A spec whose
budget is spent stops matching, so "fail the first 3 RPCs then recover"
is one arm() call.

Registered points (grep for ``faults.fire`` to verify):

  * ``service.client.send``  — client→solverd frame write
  * ``service.client.recv``  — solverd→client frame read (reader thread)
  * ``store.remote.rpc``     — RemoteBackend RPC round trip
  * ``solver.dispatch``      — device dispatch of one padded problem
  * ``solverd.handle_batch`` — daemon-side batch entry (crash the worker)
  * ``solver.audit.digest``  — shadow-audit digest comparison
                               (solver/audit.py): an armed drop/error
                               perturbs the sampled solve's live digest,
                               the injected-divergence lever proving the
                               diverged -> capture -> kt_replay loop
  * ``determinism.digest``   — flight-record canonicalization in
                               hack/determinism_harness.py: an armed
                               drop/error stamps a time.time() value
                               into the canonical record, the drill
                               proving the double-run digest compare
                               catches real nondeterminism
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

MODES = ("delay", "drop", "truncate", "crash", "error")

# fast-path gate: fire() returns immediately while this is False, so the
# disarmed hot path (every RPC, every solve) pays one global read
ARMED = False

_lock = threading.Lock()
_registry: Dict[str, List["_Spec"]] = {}


class FaultInjected(RuntimeError):
    """Raised at an injection site for drop/error (and the post-truncate
    stream kill). Sites either let it propagate (the caller's failure
    handling is the thing under test) or translate it to their native
    failure type."""

    def __init__(self, point: str, mode: str):
        super().__init__(f"injected fault at {point!r} ({mode})")
        self.point = point
        self.mode = mode


class _Spec:
    __slots__ = ("point", "mode", "arg", "remaining", "fired", "tripped",
                 "skip")

    def __init__(self, point: str, mode: str, arg: Optional[float],
                 times: Optional[int], after: int = 0):
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} (one of {MODES})")
        self.point = point
        self.mode = mode
        self.arg = arg
        self.remaining = times          # None = unbounded
        self.fired = 0
        # truncate state: first fire truncates, second kills the stream
        self.tripped = False
        # let the first `after` site hits pass through untouched: "crash
        # on the SECOND batch" is one spec, not test choreography
        self.skip = max(0, int(after))


def arm(point: str, mode: str, arg: Optional[float] = None,
        times: Optional[int] = None, after: int = 0) -> None:
    """Register one fault spec. Multiple specs may share a point (they
    fire in arm order, each consuming its own budget); `after` skips the
    first N site hits before the spec starts firing."""
    global ARMED
    spec = _Spec(point, mode, arg, times, after=after)
    with _lock:
        _registry.setdefault(point, []).append(spec)
        ARMED = True
    # the timeline's fault.inject capture point: every armed spec —
    # env-loaded, test-armed, or replayed — lands on the cluster
    # timeline so a recorded stream reproduces the fault schedule
    from karpenter_tpu.timeline import events as _tev
    from karpenter_tpu.timeline import recorder as _trec
    _trec.emit(_tev.FAULT_INJECT, name=point,
               data={"mode": mode, "arg": arg, "times": times,
                     "after": after})


def disarm(point: Optional[str] = None) -> None:
    """Clear one point, or everything when point is None."""
    global ARMED
    with _lock:
        if point is None:
            _registry.clear()
        else:
            _registry.pop(point, None)
        ARMED = bool(_registry)


def armed(point: Optional[str] = None) -> bool:
    if point is None:
        return ARMED
    with _lock:
        return bool(_registry.get(point))


def fire_count(point: str) -> int:
    """How many times any spec on `point` has fired (test assertions)."""
    with _lock:
        return sum(s.fired for s in _registry.get(point, ()))


def fire(point: str, payload: Optional[bytes] = None) -> Optional[bytes]:
    """The injection site call. Returns `payload` (possibly truncated);
    may sleep, raise FaultInjected, or _exit the process, per the armed
    spec. No-op (returns payload unchanged) while disarmed."""
    if not ARMED:
        return payload
    with _lock:
        specs = _registry.get(point)
        if not specs:
            return payload
        spec = None
        for s in specs:
            if s.remaining is not None and s.remaining <= 0 \
                    and not (s.mode == "truncate" and s.tripped):
                continue
            if s.skip > 0:
                s.skip -= 1
                continue
            spec = s
            break
        if spec is None:
            return payload
        # truncate's stream-kill follow-up fires even with budget spent,
        # exactly ONCE — consuming it retires the spec
        if not (spec.mode == "truncate" and spec.tripped):
            if spec.remaining is not None:
                spec.remaining -= 1
        spec.fired += 1
        mode, arg, tripped = spec.mode, spec.arg, spec.tripped
        if mode == "truncate":
            spec.tripped = not tripped
    if mode == "delay":
        time.sleep(arg if arg is not None else 0.05)
        return payload
    if mode in ("drop", "error"):
        raise FaultInjected(point, mode)
    if mode == "crash":
        os._exit(int(arg) if arg is not None else 137)
    # truncate: first fire shortens the payload (a torn frame on the
    # wire); the next fire at the same point raises, so the peer sees
    # mid-frame EOF instead of a clean boundary
    if tripped:
        raise FaultInjected(point, mode)
    if payload is None:
        raise FaultInjected(point, mode)
    cut = int(arg) if arg is not None else max(1, len(payload) // 2)
    return payload[:cut]


def load_env(value: Optional[str] = None) -> int:
    """Parse KARPENTER_TPU_FAULTS (or `value`) into armed specs on top of
    whatever is already armed. Returns the number of specs added.
    Malformed entries raise ValueError — a typo'd fault plan silently
    doing nothing is worse than failing loudly at startup."""
    s = (os.environ.get("KARPENTER_TPU_FAULTS", "")
         if value is None else value)
    added = 0
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        point, sep, rest = part.partition("=")
        if not sep or not point:
            raise ValueError(f"KARPENTER_TPU_FAULTS entry {part!r}: "
                             "expected point=mode[:arg][:times][:after]")
        bits = rest.split(":")
        mode = bits[0]
        arg = float(bits[1]) if len(bits) > 1 and bits[1] != "" else None
        times = int(bits[2]) if len(bits) > 2 and bits[2] != "" else None
        after = int(bits[3]) if len(bits) > 3 and bits[3] != "" else 0
        arm(point.strip(), mode.strip(), arg=arg, times=times, after=after)
        added += 1
    return added


# env arming at import: the operator/daemon picks up a fault plan from
# its environment without code changes. Tests run with the variable
# scrubbed (tests/conftest.py pops it before this module ever loads).
if os.environ.get("KARPENTER_TPU_FAULTS"):
    load_env()
