"""Fleet decision ledger + cost/efficiency accounting (ISSUE 14
tentpole parts 1 and 2).

The whole system exists to minimize fleet cost, yet until this module
the objective itself was invisible: the disruption controller computed
per-candidate prices and rejected not-cheaper replacements, and nothing
exported fleet $/hr, savings realized, or how far packing sits from the
allocatable envelope.  Two halves live here:

**The decision ledger** — a flight-recorder-style bounded ring (+ JSONL
spill) of every fleet-mutating decision: provisioning launch,
consolidation delete/replace, drift replacement, expiry, interruption
reclaim, termination.  Each :class:`LedgerRecord` carries the fleet
$/hr before and after the decision, the decision's own cost delta (the
exact floats the controller compared — ``cost_delta_hex`` is the
IEEE-754 form the acceptance checks diff), affected node/pod counts, a
reason CODE from the `solver/explain.py` registry (never a bare
string), and trace-id + flight-recorder-seq cross links so a ledger
row jumps to its solve record and span tree.  Served by
``GET /debug/ledger`` and `tools/kt_ledger.py`.

**Fleet cost & packing telemetry** — :func:`update_fleet_metrics`
prices every live node through the pricing provider and refreshes:

  * ``karpenter_tpu_fleet_hourly_cost{pool,capacity_type}``
  * ``karpenter_tpu_packing_efficiency_ratio{pool,resource}`` (and the
    fleet-wide variant) — requested vs allocatable
  * ``karpenter_tpu_stranded_capacity_units{pool,resource}``
  * ``karpenter_tpu_fleet_efficiency_lower_bound_ratio`` — actual spend
    vs a CHEAP greedy bound (total pod requests priced at the cheapest
    feasible $/resource-unit in the catalog).  Documented as the bound
    the future relaxed-LP scoring replaces; it ignores bin-packing
    integrality, so real optimal cost sits between bound and actual.

Knobs (env, all parsed HERE — the knob-registry single-owner rule):

  KARPENTER_TPU_LEDGER=off|0        disable the ledger (default: on —
                                    records are written per controller
                                    DECISION, not per solve, so the
                                    steady-state cost is zero; the
                                    record seam itself is bench-gated
                                    by `bench.py --ledger`)
  KARPENTER_TPU_LEDGER_BUFFER=N     ring size (default 512 records)
  KARPENTER_TPU_LEDGER_DIR=<dir>    spill each record as one JSONL line
                                    to <dir>/ledger-<pid>.jsonl (the
                                    durable spend trail a crashed
                                    process leaves behind; feeds
                                    tools/kt_ledger.py)
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from karpenter_tpu.utils import metrics

_ENV_GATE = "KARPENTER_TPU_LEDGER"
_ENV_BUFFER = "KARPENTER_TPU_LEDGER_BUFFER"
_ENV_DIR = "KARPENTER_TPU_LEDGER_DIR"

# the decision-source vocabulary (the `source` label of
# karpenter_tpu_ledger_records_total and every record's `source` field)
SOURCES = ("provisioning", "disruption", "drift", "expiration",
           "interruption", "termination", "preemption")


def ledger_enabled() -> bool:
    """On unless explicitly disabled — the ledger is the spend black
    box, and a record costs microseconds per controller decision."""
    from karpenter_tpu.utils.knobs import env_bool
    return env_bool(_ENV_GATE, default=True)


class LedgerRecord:
    __slots__ = ("seq", "ts", "pid", "source", "action", "reason_code",
                 "detail", "pools", "capacity_types", "nodes_delta",
                 "pods_affected", "fleet_cost_before", "fleet_cost_after",
                 "cost_delta", "cost_delta_hex", "trace_id", "flight_seq")

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw.get(name))

    def to_dict(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


class Ledger:
    """Bounded ring + optional JSONL spill; one per process
    (module-level LEDGER).  Thread-safe — controllers write from the
    reconcile loop, the operator's HTTP thread reads tails."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._buffer_size())
        self._seq = 0
        self._spill = None          # (path, file handle) once opened
        self._spill_failed = False  # one degrade, then best-effort off

    @staticmethod
    def _buffer_size() -> int:
        try:
            return max(1, int(os.environ.get(_ENV_BUFFER, "512")))
        except ValueError:
            return 512

    @property
    def enabled(self) -> bool:
        return ledger_enabled()

    def record(self, source: str, action: str, *,
               reason_code: str = "", detail: str = "",
               pools=(), capacity_types=(),
               nodes_delta: int = 0, pods_affected: int = 0,
               fleet_cost_before: Optional[float] = None,
               cost_delta: float = 0.0) -> Optional[LedgerRecord]:
        """One fleet-mutating decision.  ``cost_delta`` is the
        decision's OWN price arithmetic (the exact floats the
        controller compared: new-claim prices, retired-candidate
        prices), never a re-derived estimate — ``cost_delta_hex``
        preserves it bit-for-bit for the exactness checks.  The fleet
        $/hr before is the caller's independent sum over live nodes
        (:func:`fleet_cost`); after = before + delta."""
        if not self.enabled:
            return None
        assert source in SOURCES, source
        from karpenter_tpu.utils import flightrecorder, tracing
        after = (None if fleet_cost_before is None
                 else fleet_cost_before + cost_delta)
        with self._lock:
            self._seq += 1
            rec = LedgerRecord(
                # capture-side provenance stamp: the hex-chain check
                # and the harness's ledger digest exclude ts/pid
                seq=self._seq, ts=time.time(), pid=os.getpid(),  # kt-lint: disable=nondeterminism-source
                source=source, action=action, reason_code=reason_code,
                detail=detail, pools=sorted(set(pools)),
                capacity_types=sorted(set(capacity_types)),
                nodes_delta=nodes_delta, pods_affected=pods_affected,
                fleet_cost_before=fleet_cost_before,
                fleet_cost_after=after, cost_delta=cost_delta,
                cost_delta_hex=float(cost_delta).hex(),
                trace_id=tracing.current_trace_id(),
                flight_seq=flightrecorder.RECORDER.last_seq())
            self._ring.append(rec)
        metrics.LEDGER_RECORDS.inc(source=source)
        self._maybe_spill(rec)
        return rec

    def _maybe_spill(self, rec: LedgerRecord) -> None:
        d = os.environ.get(_ENV_DIR)
        if not d or self._spill_failed:
            return
        line = json.dumps(rec.to_dict(), default=str)
        try:
            with self._lock:
                path = os.path.join(d, f"ledger-{os.getpid()}.jsonl")
                if self._spill is None or self._spill[0] != path:
                    os.makedirs(d, exist_ok=True)
                    if self._spill is not None:
                        self._spill[1].close()
                    self._spill = (path, open(path, "a", encoding="utf-8"))
                f = self._spill[1]
                f.write(line + "\n")
                f.flush()
        except OSError:
            # spill is best-effort: a full disk degrades the spend
            # trail to ring-only, never fails a reconcile pass — but
            # counted (ISSUE 18): a lost trail tail must be visible
            metrics.SPILL_DEGRADED.inc(recorder="ledger")
            self._spill_failed = True

    def tail(self, n: int = 64, pool: Optional[str] = None,
             since: Optional[float] = None) -> List[dict]:
        """Newest-last record dicts; ``pool`` keeps records touching
        that nodepool, ``since`` keeps records with ts >= it."""
        if n <= 0:
            return []  # recs[-0:] would be the whole ring, not nothing
        with self._lock:
            recs = list(self._ring)
        if pool is not None:
            recs = [r for r in recs if pool in (r.pools or ())]
        if since is not None:
            recs = [r for r in recs if r.ts >= since]
        return [r.to_dict() for r in recs[-n:]]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def last_seq(self) -> Optional[int]:
        """Newest row's sequence number, or None while empty — the
        cross-link the timeline recorder stamps so a timeline event
        jumps to the ledger row of the decision that preceded it
        (mirror of FlightRecorder.last_seq)."""
        with self._lock:
            return self._seq if self._seq else None

    def reset(self) -> None:
        """Clear the ring and close any spill handle (tests)."""
        with self._lock:
            self._ring = deque(maxlen=self._buffer_size())
            self._seq = 0
            if self._spill is not None:
                try:
                    self._spill[1].close()
                except OSError:
                    pass
            self._spill = None
            self._spill_failed = False


LEDGER = Ledger()


def ensure_buffer(n: int) -> None:
    """Widen the module ledger's ring to hold at least `n` rows unless
    the caller already pinned KARPENTER_TPU_LEDGER_BUFFER — the
    owner-module seam for the rewind engine, whose hex-exact trajectory
    judge must see EVERY row of a replay (the default 512-row ring
    silently evicts a long day's head)."""
    if _ENV_BUFFER not in os.environ:
        os.environ[_ENV_BUFFER] = str(int(n))
        LEDGER.reset()


def load_records(path: str) -> List[dict]:
    """Parse one spilled ledger-<pid>.jsonl — or stitch every
    ledger-*.jsonl under a directory in (mtime, name) order; delegates
    to the flight recorder's torn-line-tolerant loader so the two
    spill formats can never drift in parse behavior (shared code path,
    multi-spill restart stitching included)."""
    from karpenter_tpu.utils import flightrecorder
    return flightrecorder.load_records(path, prefix="ledger")


def summarize(records: List[dict]) -> dict:
    """Spend/savings rollup over record dicts — shared by the
    `/debug/ledger` summary block and the kt_ledger CLI so the two
    surfaces can never disagree about the same records.

    Termination records are EXCLUDED from the savings/spend headline:
    termination is the mechanical settlement of an earlier
    delete/replace decision (consolidation, expiry, interruption), and
    counting both the decision's −$ and the release's −$ would double
    every saved dollar.  They still appear in by_source and the record
    table — the settlement trail matters, just not twice."""
    by_source: Dict[str, int] = {}
    savings = 0.0
    spend_added = 0.0
    last = None
    for r in records:
        src = r.get("source", "?")
        by_source[src] = by_source.get(src, 0) + 1
        delta = r.get("cost_delta") or 0.0
        if isinstance(delta, (int, float)) and src != "termination":
            if delta < 0:
                savings += -delta
            else:
                spend_added += delta
        last = r
    out = {
        "records": len(records),
        "by_source": by_source,
        "savings_dollars_per_hr": round(savings, 6),
        "spend_added_dollars_per_hr": round(spend_added, 6),
    }
    if last is not None and last.get("fleet_cost_after") is not None:
        out["fleet_cost_after_last_decision"] = last["fleet_cost_after"]
    return out


def record_claim_delete(cluster, cp, claim, *, source: str,
                        reason_code: str, detail: str,
                        node=None, price: Optional[float] = None,
                        fleet_before: Optional[float] = None,
                        pods_affected: Optional[int] = None,
                        pass_cache: Optional[dict] = None
                        ) -> Optional[LedgerRecord]:
    """The ONE delete-decision recorder shared by every claim-deleting
    controller (expiration, interruption, termination): same pricing
    resolution, same non-daemonset pod count, same −price delta — a
    schema change lands once, not three drifting times.

    The optional precomputed arguments exist for the hot callers: a
    mass spot reclaim deletes hundreds of claims in ONE reconcile, and
    re-walking the whole fleet per record (`fleet_cost` is O(nodes),
    the pod count O(pods)) would make that drain O(deleted × fleet) —
    the interruption controller computes the fleet sum once per drain
    and advances it incrementally by each record's own delta.
    `pass_cache` (an empty dict the caller resets per reconcile/drain)
    amortizes the pod count the same way: ONE pods walk per pass
    indexed by node, not one per deleted claim."""
    if not LEDGER.enabled:
        return None
    pricing = getattr(getattr(cp, "instance_types", None),
                      "pricing", None)
    if node is None:
        node = cluster.node_for_claim(claim)
    if price is None:
        price = node_price(node, pricing) if node is not None else 0.0
    if pods_affected is None:
        if pass_cache is not None:
            counts = pass_cache.get("pods_by_node")
            if counts is None:
                counts = {}
                for p in cluster.pods.list():
                    if p.node_name and not p.is_daemonset:
                        counts[p.node_name] = counts.get(p.node_name,
                                                         0) + 1
                pass_cache["pods_by_node"] = counts
            pods_affected = (counts.get(node.name, 0)
                             if node is not None else 0)
        else:
            pods_affected = (len([p for p in
                                  cluster.pods_on_node(node.name)
                                  if not p.is_daemonset])
                             if node is not None else 0)
    if fleet_before is None:
        fleet_before = fleet_cost(cluster, pricing)["total"]
    ct = node.capacity_type if node is not None else None
    return LEDGER.record(
        source, "delete", reason_code=reason_code, detail=detail,
        pools=[claim.nodepool], capacity_types=[ct] if ct else (),
        nodes_delta=-1, pods_affected=pods_affected,
        fleet_cost_before=fleet_before, cost_delta=-price)


# -- fleet cost & packing accounting --------------------------------------
def node_price(node, pricing) -> float:
    """One live node's $/hr from its offering labels; 0.0 when the
    labels or the price are missing (an unlabeled node is free in the
    ledger rather than poisoning the sum — same posture as the
    disruption controller's `_node_price`)."""
    itype, zone, ct = node.instance_type, node.zone, node.capacity_type
    if itype and zone and ct and pricing is not None:
        p = pricing.price(itype, zone, ct)
        if p is not None:
            return p
    return 0.0


def fleet_cost(cluster, pricing) -> dict:
    """The independent sum over the cluster's live nodes: total $/hr
    plus the (pool, capacity_type) breakdown the hourly-cost gauge
    exports.  This is the cross-check surface — a ledger record's
    before/after must reconcile against exactly this sum."""
    total = 0.0
    by_key: Dict[tuple, float] = {}
    for node in cluster.nodes.list(lambda n: not n.meta.deleting):
        p = node_price(node, pricing)
        total += p
        key = (node.nodepool or "", node.capacity_type or "")
        by_key[key] = by_key.get(key, 0.0) + p
    return {"total": total, "by_pool": by_key}


# previously-exported gauge series, so vanished pools/resources drop
# their series on refresh instead of reporting stale values forever
_prev_series: Dict[str, set] = {"cost": set(), "pack": set(),
                                "fleet_pack": set(), "stranded": set()}
_series_lock = threading.Lock()


def _cheapest_unit_prices(cluster, cp) -> Dict[int, float]:
    """min over purchasable offerings of $/(resource unit), per resource
    axis index — the greedy lower bound's price vector.  O(types) per
    refresh against the provider's cached type lists."""
    best: Dict[int, float] = {}
    for pool in cluster.nodepools.list(lambda p: not p.meta.deleting):
        try:
            types = cp.get_instance_types(pool.node_class_ref)
        except Exception:  # noqa: BLE001 — discovery outage: skip pool
            continue
        for it in types:
            price = None
            for off in it.offerings:
                if off.available and (price is None or off.price < price):
                    price = off.price
            if price is None:
                continue
            for ri in range(len(it.capacity.v)):
                cap = it.capacity.v[ri]
                if cap <= 0:
                    continue
                unit = price / cap
                if ri not in best or unit < best[ri]:
                    best[ri] = unit
    return best


def update_fleet_metrics(cluster, cp, pricing=None) -> dict:
    """Refresh every cost/efficiency gauge from live cluster state and
    return the summary dict (the `fleet.cost` seed).  Called each
    provisioning pass; O(nodes + pods + types) with dict-lookup
    pricing.  Best-effort — a pricing outage degrades the gauges,
    never the reconcile loop."""
    from karpenter_tpu.models.resources import RESOURCE_AXIS
    pricing = pricing if pricing is not None \
        else getattr(getattr(cp, "instance_types", None), "pricing", None)
    cost = fleet_cost(cluster, pricing)

    # fleet expected-interruption cost (ISSUE 16): Σ p × price over live
    # spot nodes under the risk model — 0 with the knob off, so the
    # gauge always reports and a knob flip shows as a step to/from zero
    from karpenter_tpu.utils.knobs import spot_risk_enabled
    risk_total = 0.0
    if spot_risk_enabled():
        from karpenter_tpu.scheduling import risk as riskmod
        for node in cluster.nodes.list(lambda n: not n.meta.deleting):
            risk_total += riskmod.expected_interruption_cost(
                node_price(node, pricing), node.instance_type or "",
                node.zone or "", node.capacity_type or "")
    metrics.SPOT_RISK_COST.set(risk_total)

    # spend by (pool, capacity_type), stale series removed
    new_cost_keys = set()
    for (pool, ct), dollars in cost["by_pool"].items():
        metrics.FLEET_HOURLY_COST.set(dollars, pool=pool,
                                      capacity_type=ct)
        new_cost_keys.add((pool, ct))
    with _series_lock:
        for pool, ct in sorted(_prev_series["cost"] - new_cost_keys):
            metrics.FLEET_HOURLY_COST.remove(pool=pool, capacity_type=ct)
        _prev_series["cost"] = new_cost_keys

    # packing efficiency + stranded capacity: requested vs allocatable.
    # One pass over nodes + one over pods (pods grouped by node name),
    # never pods_on_node per node — that is O(nodes x pods) and this
    # refresh runs every reconcile pass
    R = len(RESOURCE_AXIS)
    alloc_by_pool: Dict[str, List[float]] = {}
    req_by_pool: Dict[str, List[float]] = {}
    total_req = [0.0] * R
    total_alloc = [0.0] * R
    pool_of_node: Dict[str, str] = {}
    for node in cluster.nodes.list(lambda n: not n.meta.deleting):
        pool = node.nodepool or ""
        pool_of_node[node.name] = pool
        a = alloc_by_pool.setdefault(pool, [0.0] * R)
        req_by_pool.setdefault(pool, [0.0] * R)
        for ri in range(R):
            v = node.allocatable.v[ri]
            a[ri] += v
            total_alloc[ri] += v
    for pod in cluster.pods.list():
        pool = pool_of_node.get(pod.node_name) \
            if pod.node_name is not None else None
        if pool is None:
            continue
        q = req_by_pool[pool]
        for ri in range(R):
            v = pod.requests.v[ri]
            q[ri] += v
            total_req[ri] += v
    new_pack, new_stranded = set(), set()
    for pool, alloc in alloc_by_pool.items():
        req = req_by_pool[pool]
        for ri, name in enumerate(RESOURCE_AXIS):
            if alloc[ri] <= 0:
                continue
            metrics.PACKING_EFFICIENCY.set(
                round(req[ri] / alloc[ri], 6), pool=pool, resource=name)
            metrics.STRANDED_CAPACITY.set(
                round(alloc[ri] - req[ri], 3), pool=pool, resource=name)
            new_pack.add((pool, name))
            new_stranded.add((pool, name))
    new_fleet_pack = set()
    efficiency = {}
    for ri, name in enumerate(RESOURCE_AXIS):
        if total_alloc[ri] <= 0:
            continue
        ratio = round(total_req[ri] / total_alloc[ri], 6)
        metrics.FLEET_PACKING_EFFICIENCY.set(ratio, resource=name)
        new_fleet_pack.add((name,))
        efficiency[name] = ratio
    with _series_lock:
        for pool, name in sorted(_prev_series["pack"] - new_pack):
            metrics.PACKING_EFFICIENCY.remove(pool=pool, resource=name)
        for pool, name in sorted(_prev_series["stranded"] - new_stranded):
            metrics.STRANDED_CAPACITY.remove(pool=pool, resource=name)
        for (name,) in sorted(_prev_series["fleet_pack"] - new_fleet_pack):
            metrics.FLEET_PACKING_EFFICIENCY.remove(resource=name)
        _prev_series["pack"] = new_pack
        _prev_series["stranded"] = new_stranded
        _prev_series["fleet_pack"] = new_fleet_pack

    # greedy lower bound: total requests priced at the cheapest feasible
    # $/unit, per resource; the binding resource's cost is the bound.
    # Uncomputable (no spend, or no priced requests) removes the series
    # — the same no-stale-values discipline as every gauge above
    bound = None
    if cost["total"] > 0:
        units = _cheapest_unit_prices(cluster, cp)
        floors = [total_req[ri] * unit for ri, unit in units.items()
                  if total_req[ri] > 0]
        if floors:
            bound = max(floors)
            metrics.FLEET_EFFICIENCY_BOUND.set(
                round(min(1.0, bound / cost["total"]), 6))
    if bound is None:
        metrics.FLEET_EFFICIENCY_BOUND.remove()
    return {
        "hourly_cost_total": round(cost["total"], 6),
        "hourly_cost_by_pool": {
            f"{pool}/{ct}": round(v, 6)
            for (pool, ct), v in sorted(cost["by_pool"].items())},
        "packing_efficiency": efficiency,
        "greedy_lower_bound": None if bound is None else round(bound, 6),
    }
