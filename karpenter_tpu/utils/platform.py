"""JAX platform bootstrap shared by bench.py, benchmarks/, the solver
daemon, and tests.

Two environment facts drive this module's design (both observed, both the
cause of round 1's rc=1 bench artifact):

1. The site bootstrap (axon) exports ``JAX_PLATFORMS=axon`` process-wide
   and pins ``jax_platforms`` via ``jax.config`` at import time, and jax
   config beats the raw environment — so a process that wants CPU (tests,
   smoke benches, the solver daemon under pytest) must update the
   *config*, and our own CPU knobs (``KARPENTER_TPU_PLATFORM``,
   ``KARPENTER_TPU_FORCE_CPU``) must take priority over the inherited
   ``JAX_PLATFORMS``.
2. TPU backend init can HANG indefinitely (a claim/dial loop against the
   device relay), not just raise UNAVAILABLE — e.g. when a leftover
   kt_solverd daemon holds the chip.  An in-process retry never regains
   control from a hang, so the probe runs in a SUBPROCESS with a hard
   timeout, and only on probe success does the parent initialize in
   process.

Mirrors the reference's boot-time EC2 connectivity probe + fail-fast
diagnostic (/root/reference/pkg/operator/operator.go:209-218).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional


def _env_platform() -> Optional[str]:
    """An explicit platform request from the environment, if any.

    Our own knobs outrank the inherited JAX_PLATFORMS: the site bootstrap
    exports JAX_PLATFORMS=axon globally, so a child process asking for CPU
    via KARPENTER_TPU_* must not be overridden by it.
    """
    val = os.environ.get("KARPENTER_TPU_PLATFORM")
    if val:
        return val
    from karpenter_tpu.utils.knobs import env_bool
    if env_bool("KARPENTER_TPU_FORCE_CPU"):
        return "cpu"
    return os.environ.get("JAX_PLATFORMS") or None


def repo_root() -> str:
    """The checkout root (parent of the karpenter_tpu package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def log_attempt(record: dict) -> None:
    """Append one evidence record to BENCH_ATTEMPTS.jsonl at the repo
    root.  Shared by bench.py and the relay watchdog — append-only so
    per-attempt evidence survives artifact overwrites (ADVICE r2), and a
    write failure never takes down the attempt itself."""
    root = repo_root()
    if os.path.basename(root) in ("site-packages", "dist-packages"):
        # pip install: the package parent is not writable evidence
        # territory — keep the trail in the user cache dir instead of
        # silently swallowing every record (same guard as the compile
        # cache below)
        root = os.path.join(
            os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"),
            "karpenter_tpu")
    try:
        os.makedirs(root, exist_ok=True)
        with open(os.path.join(root, "BENCH_ATTEMPTS.jsonl"), "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass


def _parent_cmdline(ppid: str):
    """Cmdline of a process's parent, or None if the parent is gone."""
    try:
        with open(f"/proc/{ppid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return None


def scan_processes(match, orphaned_from: Optional[str] = None) -> list:
    """Best-effort list of (pid, cmdline) for processes whose cmdline
    satisfies ``match`` (excluding this process). Never raises — shared
    scan protocol for device-holder diagnostics and orphan sweeps.

    ``orphaned_from`` (a descriptive owner label, e.g. "bench.py") keeps
    only processes that are truly ORPHANED: parent gone, or reparented
    to init (ppid 1).  A process with any other live parent is spared —
    it is owned by SOMEONE (the named owner, a shell, the round driver),
    and killing owned work is far worse than occasionally failing to
    reap (the deliberate trade-off: under a child-subreaper ancestor,
    orphans reparent to the subreaper instead of init and this test
    misses them — accepted, because the only generic alternative,
    parent-cmdline matching, would kill configs a human launched from a
    shell)."""
    found = []
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid=,ppid=,args="], capture_output=True,
            text=True, timeout=5).stdout
        me = os.getpid()
        for line in out.splitlines():
            parts = line.strip().split(None, 2)
            if len(parts) != 3:
                continue
            pid_s, ppid_s, args = parts
            if not match(args) or int(pid_s) == me:
                continue
            if orphaned_from is not None and ppid_s != "1" \
                    and _parent_cmdline(ppid_s) is not None:
                continue  # live non-init parent: owned by someone
            found.append((int(pid_s), args))
    except Exception:  # noqa: BLE001 - diagnostics must never raise
        pass
    return found


def _other_device_holders() -> list:
    """Processes likely holding the accelerator: kt_solverd daemons that
    aren't us."""
    return scan_processes(lambda args: "kt_solverd" in args)


def enable_compile_cache() -> None:
    """Persistent XLA compile cache shared by every process touching the
    repo (tests, benches, config subprocesses, kt_solverd): the kernel
    compiles at a handful of bucketed shapes, and the first TPU compile
    costs 20-40 s — paying it once per shape per MACHINE instead of once
    per process keeps the 5-config bench artifact inside its wall-clock
    budget. Opt out with KARPENTER_TPU_NO_COMPILE_CACHE=1."""
    from karpenter_tpu.utils.knobs import env_bool
    if env_bool("KARPENTER_TPU_NO_COMPILE_CACHE"):
        return
    import jax
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        # repo checkout: .jax_cache next to the package (gitignored).
        # pip install: the package's parent is site-packages — often
        # read-only, and never a place to grow cache files — so fall back
        # to a per-user cache dir instead of silently losing the cache
        root = repo_root()
        candidate = os.path.join(root, ".jax_cache")
        if os.path.basename(root) in ("site-packages", "dist-packages"):
            candidate = os.path.join(
                os.environ.get("XDG_CACHE_HOME")
                or os.path.join(os.path.expanduser("~"), ".cache"),
                "karpenter_tpu", "jax")
        cache_dir = candidate
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def configure(platform: Optional[str] = None) -> Optional[str]:
    """Pin jax_platforms explicitly (config-level, beating site bootstraps).

    Resolution order: explicit arg > KARPENTER_TPU_PLATFORM >
    KARPENTER_TPU_FORCE_CPU > JAX_PLATFORMS > leave the site default.
    Returns the platform string that was pinned, or None if the site
    default was left in place.
    """
    want = platform or _env_platform()
    if want:
        import jax
        jax.config.update("jax_platforms", want)
    enable_compile_cache()
    return want


def listening_ports() -> Optional[list]:
    """TCP ports in LISTEN state, for probe-failure evidence: the axon
    device tunnel's claim leg dials a loopback relay (sitecustomize:
    AXON_POOL_SVC_OVERRIDE=127.0.0.1), so the listener set distinguishes
    'relay absent from this VM' (observed in round 4: only the VM control
    API on :2024 was listening while jax.devices() hung forever in the
    claim retry loop) from 'chip busy/held'. None = no /proc/net."""
    ports = set()
    seen_any = False
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        seen_any = True
        for line in lines:
            parts = line.split()
            if len(parts) > 3 and parts[3] == "0A":  # LISTEN
                try:
                    ports.add(int(parts[1].split(":")[1], 16))
                except (IndexError, ValueError):
                    continue
    return sorted(ports) if seen_any else None


def scrub_cpu_overrides(env: dict) -> dict:
    """Strip CPU-forcing leftovers from a child env so the child resolves
    the SITE-DEFAULT accelerator: stale KARPENTER_TPU_FORCE_CPU /
    KARPENTER_TPU_PLATFORM / JAX_PLATFORMS=cpu from earlier degraded-mode
    tooling would otherwise make an accelerator probe (or the bench it
    triggers) silently report "cpu" even with the relay live."""
    env.pop("KARPENTER_TPU_FORCE_CPU", None)
    # value-checked: an operator's ACCELERATOR pin (e.g. =tpu) must
    # survive the scrub — only cpu leftovers are stripped
    if env.get("KARPENTER_TPU_PLATFORM") == "cpu":
        env.pop("KARPENTER_TPU_PLATFORM")
    if env.get("JAX_PLATFORMS") == "cpu":
        # the site bootstrap pins the accelerator via jax.config at
        # import time, which survives dropping the env var
        env.pop("JAX_PLATFORMS")
    return env


def probe_backend(platform: Optional[str], timeout_s: float,
                  log=None, attempt_log=None) -> dict:
    """Initialize the backend in a THROWAWAY subprocess with a hard kill
    timeout — the only way to survive an init that hangs rather than
    raises.  Returns an evidence record: ``outcome`` ok|hang|error, plus
    the obtained ``platform`` on ok.  Failure evidence (rc, stderr tail,
    hang-vs-error, relay reachability) also goes through ``attempt_log``
    so artifacts record the ACTUAL probe error, not just the eventual
    fallback (VERDICT r3 #1)."""
    log = log or (lambda m: print(m, file=sys.stderr, flush=True))
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
        env.pop("KARPENTER_TPU_FORCE_CPU", None)
        env["KARPENTER_TPU_PLATFORM"] = platform
    else:
        scrub_cpu_overrides(env)
    code = (
        "import os\n"
        "from karpenter_tpu.utils.platform import configure\n"
        "configure()\n"
        "import jax\n"
        "ds = jax.devices()\n"
        "print('PROBE-OK', ds[0].platform, len(ds), flush=True)\n"
    )
    env["PYTHONPATH"] = repo_root() + os.pathsep + env.get("PYTHONPATH", "")
    rec = {"stage": "probe", "want": platform or "<site-default>",
           "listening_ports": listening_ports(), "ts": time.time()}
    t0 = time.monotonic()
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        # a hang (vs an error) is the signature of the claim leg spinning
        # against a dead/absent relay: the axon client retries the
        # /v1/claim dial forever instead of raising
        err = (e.stderr or b"")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        rec.update(outcome="hang", timeout_s=timeout_s,
                   stderr_tail=err.strip()[-400:])
        log(f"[platform] probe hung past {timeout_s:.0f}s (backend init "
            "wedged — relay down or device held elsewhere?); "
            f"listening_ports={rec['listening_ports']}")
        if attempt_log:
            attempt_log(rec)
        return rec
    rec["probe_secs"] = round(time.monotonic() - t0, 1)
    # match the marker as the first token of its own line: a library
    # writing to stdout without a trailing newline must neither fake a
    # success (bare substring test) nor crash the platform extraction
    ok_line = next((ln for ln in proc.stdout.splitlines()
                    if ln.startswith("PROBE-OK ")), None)
    if proc.returncode == 0 and ok_line:
        rec.update(outcome="ok", platform=ok_line.split()[1])
        if attempt_log:
            # success evidence too: a run that reached the device after
            # two hangs must not read as all-failures in the log
            attempt_log(rec)
        return rec
    tail = (proc.stderr or proc.stdout).strip()
    rec.update(outcome="error", rc=proc.returncode,
               stderr_tail=tail[-400:])
    log(f"[platform] probe failed rc={proc.returncode}: "
        f"{tail.splitlines()[-1][:200] if tail else '<no output>'}")
    if attempt_log:
        attempt_log(rec)
    return rec


def _terminate(send, target: int, label: str, grace_s: float, log) -> None:
    """Shared graceful-eviction protocol: SIGTERM, poll for exit, SIGKILL
    only as the last resort. A SIGKILLed holder never runs its PJRT
    teardown, and the remote pool can then keep the dead client's claim
    until its lease times out — wedging the device for every later
    process far longer than the grace period spent here.  ``send`` is
    os.kill (single pid) or os.killpg (whole group)."""
    log = log or (lambda m: print(m, file=sys.stderr, flush=True))
    try:
        send(target, signal.SIGTERM)
    except OSError:
        return
    deadline = time.time() + grace_s
    while time.time() < deadline:
        try:
            send(target, 0)
        except OSError:
            return  # exited cleanly
        time.sleep(0.25)
    try:
        send(target, signal.SIGKILL)
        log(f"[platform] {label} {target} ignored SIGTERM for "
            f"{grace_s:.0f}s; SIGKILLed (device lease may linger)")
    except OSError:
        pass


def terminate_holder(pid: int, grace_s: float = 10.0, log=None) -> None:
    """Gracefully evict one chip-holding process."""
    _terminate(os.kill, pid, "pid", grace_s, log)


def terminate_group(pgid: int, grace_s: float = 10.0, log=None) -> None:
    """terminate_holder for a whole process GROUP (killpg): needed when the
    target is a session leader whose chip-holding grandchildren (platform
    probe subprocesses) would survive a single-pid TERM."""
    _terminate(os.killpg, pgid, "pgid", grace_s, log)


def initialize(platform: Optional[str] = None, retries: int = 3,
               backoff_s: float = 5.0, probe_timeout_s: Optional[float] = None,
               cpu_fallback: bool = True, kill_holders: bool = False,
               log=None, attempt_log=None) -> str:
    """Probe the requested (or site-default) backend out of process, then
    configure + initialize in process; returns the platform of the device
    actually obtained ("tpu", "cpu", ...).

    Between failed probes: names kt_solverd processes that may hold the
    chip (optionally SIGKILLs them when ``kill_holders`` — safe only for
    the benchmark driver, which owns the machine) and retries with
    backoff.  After all retries, falls back to CPU when ``cpu_fallback``
    instead of crashing the artifact.
    """
    log = log or (lambda m: print(m, file=sys.stderr, flush=True))
    want = platform or _env_platform()
    if probe_timeout_s is None:
        probe_timeout_s = float(os.environ.get(
            "KARPENTER_TPU_PROBE_TIMEOUT", "180"))

    if want == "cpu":
        configure("cpu")
        import jax
        return jax.devices()[0].platform

    ok = False
    for attempt in range(max(1, retries)):
        if probe_backend(want, probe_timeout_s, log,
                         attempt_log=attempt_log)["outcome"] == "ok":
            ok = True
            break
        for pid, args in _other_device_holders():
            log(f"[platform] possible device holder: pid {pid}: {args[:120]}")
            if kill_holders:
                terminate_holder(pid, log=log)
                log(f"[platform] evicted pid {pid}")
        if attempt + 1 < retries:
            time.sleep(backoff_s * (attempt + 1))

    if ok:
        configure(want)
        import jax
        return jax.devices()[0].platform
    if cpu_fallback:
        log("[platform] accelerator unavailable after retries; falling "
            "back to CPU so the artifact is still produced")
        configure("cpu")
        import jax
        try:
            jax.extend.backend.clear_backends()
        except Exception:  # noqa: BLE001
            pass
        return jax.devices()[0].platform
    raise RuntimeError(
        f"JAX backend {want or 'default'} unavailable after {retries} probes")
