"""Flight recorder: an always-on, bounded, per-process ring of solve
records (ISSUE 9 tentpole part 1).

Every pass through the solver's `_solve_attempt` seam (and every fused
batch the solverd backend dispatches) appends one :class:`FlightRecord`:
catalog identity, an encoded-problem fingerprint, the resolved execution
knobs (mesh/delta/pipeline/node axis), the per-phase timings from
`last_phase_ms`, the delta outcome + fallback reason, retrace count,
device-memory watermark, a result digest (nodes / bit-exact cost), and
the active trace id.  The point: a production parity bug stops being
"reproduce it by luck" — the record says exactly *what* the solve saw
and *what* it answered, and with full capture enabled the problem itself
is on disk for `tools/kt_replay.py` to re-execute deterministically.

Modes (all env-resolved per record so tests and operators can flip them
without rebuilding the solver):

  KARPENTER_TPU_FLIGHT=off|0        disable entirely (default: on — the
                                    fingerprint-only record is budgeted
                                    <1% of the headline solve p50,
                                    bench-asserted by `bench.py --flight`)
  KARPENTER_TPU_FLIGHT_BUFFER=N     ring size (default 256 records)
  KARPENTER_TPU_FLIGHT_DIR=<dir>    additionally spill each record as one
                                    JSONL line to <dir>/flight-<pid>.jsonl
                                    (the durable tail a crashed process
                                    leaves behind)
  KARPENTER_TPU_FLIGHT_CAPTURE=1    with FLIGHT_DIR set: pickle the FULL
                                    problem (ScheduleInput + node cap) to
                                    <dir>/capture-<pid>-<seq>.pkl and
                                    reference it from the record — the
                                    one-command-repro input for kt_replay

Fingerprints are sha256 over the SMALL encoded arrays (group axis +
existing axis + limits — kilobytes at the 50k-pod shape, never the
[G, O] mask), so the default record costs microseconds.  Two solves
with the same fingerprint saw the same problem as far as the kernel's
group/exist/limit inputs are concerned; the full capture is the
authoritative artifact.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from karpenter_tpu.utils import metrics

_ENV_GATE = "KARPENTER_TPU_FLIGHT"
_ENV_BUFFER = "KARPENTER_TPU_FLIGHT_BUFFER"
_ENV_DIR = "KARPENTER_TPU_FLIGHT_DIR"
_ENV_CAPTURE = "KARPENTER_TPU_FLIGHT_CAPTURE"


def recording_enabled() -> bool:
    """On unless explicitly disabled — the recorder is the always-on
    black box, and its default path must stay cheap enough to leave on
    (`bench.py --flight` asserts <1% of the headline p50)."""
    from karpenter_tpu.utils.knobs import env_bool
    return env_bool(_ENV_GATE, default=True)


def _sha16(*chunks) -> str:
    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return h.hexdigest()[:16]


def catalog_identity(cat) -> dict:
    """Compact identity of a CatalogEncoding: enough to tell two
    catalogs apart (column count, grid stride, pool names, price
    digest) without hashing the multi-MB column matrices.  Memoized on
    the encoding object — one price-array hash per catalog identity,
    not per solve (the <1% overhead budget)."""
    ident = getattr(cat, "_flight_identity", None)
    if ident is None:
        ident = {
            "columns": len(cat.columns),
            "zc": cat.zc,
            "pools": [p.meta.name for p in cat.pools],
            "price_sha": _sha16(cat.col_price.tobytes()),
        }
        try:
            cat._flight_identity = ident
        except AttributeError:
            pass
    return ident


def problem_fingerprint(enc) -> str:
    """sha256 (truncated) over the group-axis and exist-axis encoded
    arrays — the per-problem kernel inputs that are small (KBs at the
    50k shape).  The [G, O] mask is deliberately excluded from the
    default fingerprint (it can be ~MBs); the full capture carries the
    authoritative problem."""
    return _sha16(
        enc.group_req.tobytes(), enc.group_count.tobytes(),
        enc.exist_remaining.tobytes(), enc.pool_limit.tobytes(),
        str((enc.n_groups, enc.n_columns, enc.n_domains,
             len(enc.existing))).encode())


def result_digest(res) -> dict:
    """Bit-exact digest of a ScheduleResult: node count, total price as
    both a readable float and its IEEE hex form (the replay CLI compares
    the hex — "close enough" is exactly the parity bug class the
    recorder exists to catch), plus placement counts."""
    price = res.total_price()
    return {
        "nodes": res.node_count(),
        "price": round(price, 4),
        "price_hex": float(price).hex(),
        "existing_assignments": len(res.existing_assignments),
        "unschedulable": len(res.unschedulable),
    }


class FlightRecord:
    __slots__ = ("seq", "ts", "pid", "kind", "trace_id", "catalog",
                 "fingerprint", "pods", "groups", "knobs", "phase_ms",
                 "delta", "retraces", "device_memory_peak_bytes",
                 "result", "capture")

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw.get(name))

    def to_dict(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


class FlightRecorder:
    """Bounded ring + optional JSONL spill.  One per process
    (module-level RECORDER); thread-safe — the operator's solve path,
    the solverd batcher thread, and the dashboard reader all touch it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._buffer_size())
        self._seq = 0
        # captures number themselves: predicting the NEXT record seq
        # would collide under concurrent solves (and stall at 1 if the
        # ring gate ever diverged from the capture gate)
        self._capture_seq = 0
        self._spill = None          # (path, file handle) once opened
        self._spill_failed = False  # one warning, then best-effort off

    @staticmethod
    def _buffer_size() -> int:
        try:
            return max(1, int(os.environ.get(_ENV_BUFFER, "256")))
        except ValueError:
            return 256

    @property
    def enabled(self) -> bool:
        return recording_enabled()

    def capture_enabled(self) -> bool:
        """Full problem capture: opt-in, needs a spill directory, and
        requires the recorder itself on — a capture no record ever
        references is an orphan artifact, not a repro."""
        from karpenter_tpu.utils.knobs import env_bool
        return (self.enabled
                and env_bool(_ENV_CAPTURE)
                and bool(os.environ.get(_ENV_DIR)))

    def record(self, **fields) -> Optional[FlightRecord]:
        if not self.enabled:
            return None
        with self._lock:
            self._seq += 1
            # capture-side provenance stamp: every digest/fingerprint
            # canonicalization excludes ts (and pid)
            rec = FlightRecord(seq=self._seq, ts=time.time(),  # kt-lint: disable=nondeterminism-source
                               pid=os.getpid(), **fields)
            self._ring.append(rec)
        self._maybe_spill(rec)
        return rec

    def capture_problem(self, payload, force: bool = False) -> Optional[str]:
        """Pickle the full problem next to the spill file; returns the
        capture path (referenced from the record) or None.  Called by
        the solver BEFORE the solve runs, so a crash mid-solve still
        leaves the input on disk — the black-box discipline.

        ``force=True`` (the shadow-audit divergence path) captures even
        when the per-solve KARPENTER_TPU_FLIGHT_CAPTURE opt-in is off:
        a detected divergence is exactly the problem worth a repro
        artifact, and waiting for the operator to re-arm capture means
        hoping it recurs.  A spill directory is still required — there
        is nowhere else to put the artifact."""
        if force:
            if not (self.enabled and os.environ.get(_ENV_DIR)):
                return None
        elif not self.capture_enabled():
            return None
        import pickle
        d = os.environ.get(_ENV_DIR)
        try:
            os.makedirs(d, exist_ok=True)
            with self._lock:
                self._capture_seq += 1
                seq = self._capture_seq
            path = os.path.join(d, f"capture-{os.getpid()}-{seq}.pkl")
            with open(path, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            return path
        except OSError:
            return None

    def _maybe_spill(self, rec: FlightRecord) -> None:
        d = os.environ.get(_ENV_DIR)
        if not d or self._spill_failed:
            return
        line = json.dumps(rec.to_dict(), default=str)
        try:
            with self._lock:
                path = os.path.join(d, f"flight-{os.getpid()}.jsonl")
                if self._spill is None or self._spill[0] != path:
                    os.makedirs(d, exist_ok=True)
                    if self._spill is not None:
                        self._spill[1].close()
                    self._spill = (path, open(path, "a", encoding="utf-8"))
                f = self._spill[1]
                f.write(line + "\n")
                f.flush()
        except OSError:
            # spill is best-effort: a full disk must degrade the black
            # box to ring-only, never fail a solve — but counted, so a
            # fleet losing its on-disk tail shows on a dashboard
            metrics.SPILL_DEGRADED.inc(recorder="flight")
            self._spill_failed = True

    def tail(self, n: int = 32,
             trace_id: Optional[str] = None) -> List[dict]:
        if n <= 0:
            return []  # recs[-0:] would be the whole ring, not nothing
        with self._lock:
            recs = list(self._ring)
        if trace_id is not None:
            recs = [r for r in recs if r.trace_id == trace_id]
        return [r.to_dict() for r in recs[-n:]]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def last_seq(self) -> Optional[int]:
        """The newest record's sequence number, or None while empty —
        the cross-link the decision ledger stamps so a ledger row jumps
        to the flight record of the solve that backed it."""
        with self._lock:
            return self._seq if self._seq else None

    def reset(self) -> None:
        """Clear the ring and close any spill handle (tests)."""
        with self._lock:
            self._ring = deque(maxlen=self._buffer_size())
            self._seq = 0
            self._capture_seq = 0
            if self._spill is not None:
                try:
                    self._spill[1].close()
                except OSError:
                    pass
            self._spill = None
            self._spill_failed = False


RECORDER = FlightRecorder()


def load_records(path: str, prefix: str = "flight") -> List[dict]:
    """Parse one spilled <prefix>-<pid>.jsonl, or — when `path` is a
    DIRECTORY — stitch every <prefix>-*.jsonl in it, ordered by
    (mtime, name): each process lifetime leaves its own per-pid spill,
    and a restart replay must see the whole sequence in the order the
    segments were written, with the filename as the deterministic
    tie-break (ROADMAP item 5 / ISSUE 18 satellite — an unsorted
    listdir here is exactly what the nondeterminism-source rule flags).
    Malformed lines (a torn write from a crashed process — exactly when
    the file matters most) are skipped, not fatal."""
    if os.path.isdir(path):
        spills = sorted(
            (os.path.join(path, f) for f in os.listdir(path)
             if f.startswith(prefix + "-") and f.endswith(".jsonl")),
            key=lambda p: (os.path.getmtime(p), p))
        out: List[dict] = []
        for p in spills:
            out.extend(load_records(p))
        return out
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
