"""In-process Prometheus-style metrics registry.

The reference exports its contract on :8000 via prometheus client_golang;
the metric *names* are the compatibility surface (SURVEY §5: "these metric
names are the contract for the baseline comparison") — catalogued in
website/content/en/preview/reference/metrics.md. This module provides the
same families over a dependency-free registry with Prometheus text
exposition, so dashboards written for the reference keep working.

Key families (metrics.md):
  karpenter_provisioner_scheduling_duration_seconds           :102
  karpenter_provisioner_scheduling_simulation_duration_seconds
  karpenter_provisioner_scheduling_queue_depth
  karpenter_disruption_evaluation_duration_seconds            :137
  karpenter_disruption_eligible_nodes
  karpenter_nodeclaims_{launched,registered,initialized,terminated}_total
                                                              :27-48
  karpenter_interruption_received_messages_total              :107-116
  karpenter_cloudprovider_duration_seconds   (metrics.Decorate wrapper,
                                              cmd/controller/main.go:43)
  karpenter_cloudprovider_errors_total
  karpenter_cloudprovider_batcher_batch_size (pkg/batcher/metrics.go)
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        return tuple(labels[k] for k in self.label_names)

    @staticmethod
    def _esc(value) -> str:
        """Label-value escaping per the Prometheus text exposition spec:
        backslash, double-quote, and line-feed must be escaped or the
        rendered line is invalid text format (a selector value like
        `zone="us-east\\1"` would otherwise break every scraper)."""
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @classmethod
    def _fmt_labels(cls, names, values) -> str:
        if not names:
            return ""
        inner = ",".join(f'{n}="{cls._esc(v)}"' for n, v in zip(names, values))
        return "{" + inner + "}"


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, tuple(label_names))
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def remove(self, **labels) -> None:
        """Drop one series — for label values whose identity is
        process-ephemeral and can never recur (the tenant scheduler's
        connection-derived tenants), keeping the series would grow the
        exposition unboundedly; the reference deletes vanished
        per-type series the same way."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        for key, v in sorted(self._values.items()):
            out.append(
                f"{self.name}{self._fmt_labels(self.label_names, key)} {v}")
        return out


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value
    # remove() inherited: catalog gauges delete series for vanished
    # types/offerings on rebuild, or a removed offering keeps reporting
    # stale values forever


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, tuple(label_names))
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, **labels):
        """Context manager: observe the elapsed wall time."""
        metric = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                metric.observe(time.perf_counter() - self.t0, **labels)
                return False

        return _Timer()

    def count(self, **labels) -> int:
        return self._totals.get(self._key(labels), 0)

    def sum(self, **labels) -> float:
        return self._sums.get(self._key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        for key in sorted(self._totals):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum = self._counts[key][i]
                names = self.label_names + ("le",)
                values = key + (repr(b),)
                out.append(f"{self.name}_bucket"
                           f"{self._fmt_labels(names, values)} {cum}")
            names = self.label_names + ("le",)
            out.append(f"{self.name}_bucket"
                       f"{self._fmt_labels(names, key + ('+Inf',))} "
                       f"{self._totals[key]}")
            lbl = self._fmt_labels(self.label_names, key)
            out.append(f"{self.name}_sum{lbl} {self._sums[key]}")
            out.append(f"{self.name}_count{lbl} {self._totals[key]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help_="", labels=()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore

    def gauge(self, name, help_="", labels=()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore

    def histogram(self, name, help_="", labels=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(
            Histogram(name, help_, labels, buckets))  # type: ignore

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition of every registered family."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every metric's samples. Registrations are kept — module-level
        metric objects stay live and exported; only their values clear."""
        with self._lock:
            for m in self._metrics.values():
                for attr in ("_values", "_counts", "_sums", "_totals"):
                    d = getattr(m, attr, None)
                    if d is not None:
                        d.clear()


# the process-global registry (the role of prometheus.DefaultRegisterer)
REGISTRY = Registry()


def _h(name, help_, labels=()):
    return REGISTRY.histogram(name, help_, labels)


def _c(name, help_, labels=()):
    return REGISTRY.counter(name, help_, labels)


def _g(name, help_, labels=()):
    return REGISTRY.gauge(name, help_, labels)


# -- the contract families (metrics.md) ---------------------------------
SCHEDULING_DURATION = _h(
    "karpenter_provisioner_scheduling_duration_seconds",
    "Duration of one scheduling solve.")
SCHEDULING_SIMULATION_DURATION = _h(
    "karpenter_provisioner_scheduling_simulation_duration_seconds",
    "Duration of one disruption scheduling simulation.")
PROVISIONER_BACKLOG_AGE = _g(
    "karpenter_tpu_provisioner_backlog_age_seconds",
    "Age of the oldest still-pending pod the provisioner has seen — the "
    "degraded-mode liveness signal: under oracle fallback with load "
    "shedding, a healthy backlog drains pass by pass and this converges "
    "to zero; growth means the loop is not keeping up.")
SCHEDULING_QUEUE_DEPTH = _g(
    "karpenter_provisioner_scheduling_queue_depth",
    "Pending pods awaiting a scheduling pass.")
RELAXATION_DURATION = _h(
    "karpenter_tpu_solver_relaxation_duration_seconds",
    "Wall-clock of the preference-relaxation outer loop per solve.")
RELAXATION_BUDGET_EXCEEDED = _c(
    "karpenter_tpu_solver_relaxation_budget_exceeded_total",
    "Solves whose relaxation loop hit its wall-clock budget and degraded "
    "remaining stragglers to the oracle.")
SOLVER_SHED_PODS = _c(
    "karpenter_tpu_solver_fallback_shed_pods_total",
    "Pods deferred to the next provisioning pass because the oracle "
    "fallback capped its batch (device path unavailable).")
DISRUPTION_EVALUATION_DURATION = _h(
    "karpenter_disruption_evaluation_duration_seconds",
    "Duration of one disruption evaluation pass.", ("method",))
DISRUPTION_ELIGIBLE_NODES = _g(
    "karpenter_disruption_eligible_nodes",
    "Candidates eligible for disruption in the last pass.", ("method",))
DISRUPTION_ACTIONS = _c(
    "karpenter_disruption_actions_performed_total",
    "Disruption commands executed.", ("method",))
NODECLAIMS_LAUNCHED = _c(
    "karpenter_nodeclaims_launched_total",
    "NodeClaims launched.", ("nodepool",))
NODECLAIMS_REGISTERED = _c(
    "karpenter_nodeclaims_registered_total",
    "NodeClaims whose node registered.", ("nodepool",))
NODECLAIMS_INITIALIZED = _c(
    "karpenter_nodeclaims_initialized_total",
    "NodeClaims fully initialized.", ("nodepool",))
NODECLAIMS_TERMINATED = _c(
    "karpenter_nodeclaims_terminated_total",
    "NodeClaims terminated.", ("nodepool",))
RECONCILE_ERRORS = _c(
    "karpenter_tpu_controller_reconcile_errors_total",
    "Errors a controller swallowed to keep the manager loop alive "
    "(retryable cloud outages, discovery failures), by controller. A "
    "silent swallow hides a persistent outage; this family is the "
    "kt-lint exception-hygiene contract's metrics half.", ("controller",))
INTERRUPTION_MESSAGES = _c(
    "karpenter_interruption_received_messages_total",
    "Interruption-queue messages received.", ("message_type",))
CLOUDPROVIDER_DURATION = _h(
    "karpenter_cloudprovider_duration_seconds",
    "CloudProvider method latency.", ("method",))
CLOUDPROVIDER_ERRORS = _c(
    "karpenter_cloudprovider_errors_total",
    "CloudProvider method errors.", ("method",))
BATCHER_BATCH_SIZE = REGISTRY.histogram(
    "karpenter_cloudprovider_batcher_batch_size",
    "Items per executed batch.", ("batcher",),
    buckets=(1, 2, 5, 10, 25, 50, 100, 250, 500, 1000))
# -- TPU-solver observability (new vs the reference): distinguishing the
# -- device path from the split path from the oracle fallback is the only
# -- way to notice the latency SLO silently degrading 1000x (VERDICT r1
# -- weak #6: "no metric distinguishes solver-path from fallback-path")
SOLVER_SOLVES = _c(
    "karpenter_tpu_solver_solves_total",
    "Scheduling solves by execution path.", ("path",))
# last_phase_ms promoted to a first-class family: the per-solve phase
# breakdown (pregroup/encode/pad/device/repair/decode) was visible only
# in bench stdout, invisible to /metrics — the opaque segments now
# dominating the 200 ms budget (BENCH_r05: device 50.7 ms, decode
# 13.8 ms) must be attributable from the operator's scrape
SOLVER_PHASE_DURATION = _h(
    "karpenter_tpu_solver_phase_duration_seconds",
    "Per-phase wall-clock of one device solve, by execution path "
    "(solve = single-problem attempt, sweep = batched consolidation "
    "sweep).", ("phase", "path"))
# -- incremental delta solves (solver/delta.py): the O(churn) steady-state
# -- path's observable half.  outcome="delta" passes reused the cached
# -- prefix; outcome="fallback" passes ran the full solve for a
# -- conservative reason (topology, node churn, catalog change, bucket
# -- crossing, cold cache) — every fallback is counted here, never silent
SOLVER_DELTA_PASSES = _c(
    "karpenter_tpu_solver_delta_passes_total",
    "Passes through the delta-solve seam by outcome: delta = the "
    "restricted suffix solve ran (result bit-identical to a full "
    "re-solve), fallback = a conservative exactness guard sent the "
    "pass to the full path.", ("outcome",))
SOLVER_DELTA_GROUPS_REENCODED = _g(
    "karpenter_tpu_solver_delta_groups_reencoded",
    "Pod classes freshly re-encoded in the last delta pass (the churn "
    "the pass actually paid for; unchanged suffix classes reuse their "
    "cached rows).")
# -- event-driven incremental group index (solver/incr.py, ISSUE 20):
# -- the O(churn) grouping seam's observable half, same counted
# -- discipline as the delta seam — a pass where the index could have
# -- engaged either resolves the dirty set with index probes or names a
# -- conservative fallback reason and walks
SOLVER_INCR_PASSES = _c(
    "karpenter_tpu_solver_incr_passes_total",
    "Passes through the incremental-index seam by outcome: incr = the "
    "pass's groups were assembled from the event-maintained index "
    "(bit-identical to the grouping walk), fallback = a conservative "
    "index-unusable condition (cold/flood/drift/pods/nodes/order) "
    "degraded the grouping to the O(cluster) walk.", ("outcome",))
# -- speculative chunked G-axis pipeline (solver/solve.py _try_spec,
# -- ISSUE 19): the chunked-chain seam's observable half, same counted
# -- discipline as the delta seam — a pass either chunks or names a
# -- conservative fallback reason, and every speculated chunk either
# -- commits bit-exactly or pays a counted repair re-dispatch
SOLVER_SPEC_PASSES = _c(
    "karpenter_tpu_solver_spec_passes_total",
    "Passes through the speculative-chunk seam by outcome: spec = the "
    "G axis ran as a pipelined chain of seeded chunk solves (result "
    "bit-identical to the sequential program), fallback = a "
    "conservative exactness guard sent the pass to the single-program "
    "path.", ("outcome",))
SOLVER_SPEC_CHUNKS = _c(
    "karpenter_tpu_solver_spec_chunks_total",
    "Speculated chunks by commit verdict: committed = the speculated "
    "entry seed matched the true exit state bit-for-bit (the in-flight "
    "solve IS the sequential program's), repaired = the seed diverged "
    "(or speculation was declined) and the chunk re-solved from the "
    "true seed — every divergence is counted here, never silent.",
    ("outcome",))
# -- solver-service availability (ISSUE 7): the crash-isolation story's
# -- observable half — without these, a daemon crash-loop looks identical
# -- to a healthy idle service from the operator's scrape
SERVICE_RETRIES = _c(
    "karpenter_tpu_service_retries_total",
    "Solver-service RPCs retried after a transport failure (connect/"
    "send/receive/timeout), before the breaker or the caller gave up.")
SERVICE_BREAKER_STATE = _g(
    "karpenter_tpu_service_breaker_state",
    "Solver-service circuit breaker state: 0=closed (healthy), 1=open "
    "(failing fast, control plane in degraded mode), 2=half-open (one "
    "probe in flight).")
SERVICE_WORKER_RESTARTS = _c(
    "karpenter_tpu_service_worker_restarts_total",
    "Supervised kt_solverd worker processes restarted after an "
    "unexpected exit (crash containment; a climbing series means a "
    "crash loop the backoff is absorbing).")
# -- multi-tenant solverd dispatch (ISSUE 11): the tenant-aware
# -- scheduler's observable half — per-tenant demand/fairness/shedding
# -- and the cross-tenant fusion the shared fleet's throughput rides on
SERVICE_TENANT_REQUESTS = _c(
    "karpenter_tpu_service_tenant_requests_total",
    "Schedule requests admitted to the solverd tenant scheduler, by "
    "tenant (the client-declared tenant field; connection-derived when "
    "absent). Per-tenant share of this family is the fairness "
    "denominator.", ("tenant",))
SERVICE_TENANT_SHED = _c(
    "karpenter_tpu_service_tenant_shed_total",
    "Requests the tenant scheduler shed, counted never silent: "
    "reason=admission (queue at its bound, lowest priority loses), "
    "reason=deadline (the caller's deadline passed at ingest or while "
    "queued). Every shed is answered with an explicit shed response "
    "carrying the backpressure hint.", ("tenant", "reason"))
SERVICE_TENANT_QUEUE_DEPTH = _g(
    "karpenter_tpu_service_tenant_queue_depth",
    "Requests currently waiting in one tenant's scheduler queue "
    "(excludes the C++ window backlog, which rides the backpressure "
    "hints instead).", ("tenant",))
SERVICE_FUSED_BATCHES = _c(
    "karpenter_tpu_service_fused_batches_total",
    "Fused device dispatches by whether the batch mixed tenants "
    "(cross_tenant=yes/no). A healthy shared fleet under concurrent "
    "compatible traffic runs mostly yes; all-no under multi-tenant "
    "load means buckets aren't aligning (check catalog fingerprints "
    "and the warmup lattice).", ("cross_tenant",))
SERVICE_FUSED_BATCH_SIZE = REGISTRY.histogram(
    "karpenter_tpu_service_fused_batch_size",
    "Requests per fused solverd device dispatch (the occupancy the "
    "saturation bench gates on).",
    buckets=(1, 2, 4, 8, 16, 32, 64))
SOLVER_RESIDUE_PODS = _c(
    "karpenter_tpu_solver_residue_pods_total",
    "Pods solved host-side as split-solve residue.")
# -- placement provenance (ISSUE 13): the decision-observability layer —
# -- every final unschedulable verdict carries a registry reason code
# -- (solver/explain.py, the one enum owner), and the kernel's explain
# -- aux attributes candidate eliminations to constraint classes
UNSCHEDULABLE_PODS = _c(
    "karpenter_tpu_unschedulable_pods_total",
    "Pods reported unschedulable by the provisioning pass, by registry "
    "reason code (solver/explain.py). reason=Legacy marks a plain-string "
    "reason from an unregistered producer — kt-lint's reason-literal "
    "check keeps this at zero.", ("reason",))
# -- gang scheduling (ISSUE 15): atomic multi-node placement outcomes
GANG_PLACEMENTS = _c(
    "karpenter_tpu_gang_placements_total",
    "Gang placement outcomes per provisioning pass (one increment per "
    "gang): outcome=placed when every member landed, outcome=stranded "
    "when the gang stranded whole — by the atomicity invariant there "
    "is no third outcome (a partial gang is a bug, counted on "
    "karpenter_tpu_solver_gang_repairs_total).", ("outcome",))
# -- priority & preemption (ISSUE 16)
PREEMPTIONS = _c(
    "karpenter_tpu_preemptions_total",
    "Preemption plan executions (one increment per plan): "
    "outcome=evicted when every victim drained (plans are atomic — a "
    "gang victim evicts whole), outcome=blocked when any victim failed "
    "its eviction gate and the WHOLE plan was skipped, outcome=stale "
    "when the plan's victims were already gone by execution time.",
    ("outcome",))
SPOT_RISK_COST = _g(
    "karpenter_tpu_spot_risk_cost",
    "Fleet expected-interruption cost in $/hr: Σ over spot nodes of "
    "p(interruption) × price under the KARPENTER_TPU_SPOT_RISK model — "
    "the quantity the risk-weighted objective minimizes at equal "
    "coverage (0 when the knob is off or the fleet is on-demand).")
SOLVER_GANG_REPAIRS = _c(
    "karpenter_tpu_solver_gang_repairs_total",
    "Gang fills the host-side atomicity safety net rolled back "
    "(partial or cross-domain placement out of the kernel) — expected "
    "to stay at zero; any increment is a kernel gang-commit bug made "
    "visible instead of a silently split gang.")
SOLVER_HOST_REPAIRS = _c(
    "karpenter_tpu_solver_host_repairs_total",
    "Kernel placements the host-side repair nets rolled back or "
    "trimmed, by kind: whole_node = a co-location group stranded "
    "atomically (split across nodes out of the kernel), topology = "
    "placements stripped above the final skew ceiling.  Each repair "
    "is a counted degrade (the oracle rescue then re-seats the pods), "
    "never a silent rewrite.", ("kind",))
SPILL_DEGRADED = _c(
    "karpenter_tpu_spill_degraded_total",
    "Spill-to-disk writes abandoned (OSError — full disk, dead mount) "
    "by recorder: flight, ledger, timeline.  The black box degrades "
    "to ring-only and keeps serving; a non-zero rate means restart "
    "replay is losing its tail and the disk needs attention.",
    ("recorder",))
SOLVER_CONSTRAINT_ELIM = _c(
    "karpenter_tpu_solver_constraint_eliminations_total",
    "Catalog-column eliminations attributed per constraint class by the "
    "solver's explain aux (KARPENTER_TPU_EXPLAIN): compat/price are the "
    "host encode-side classes, fit/limit/topology/whole_node/slots the "
    "kernel-side ones. The fleet-level 'which constraint is binding' "
    "signal.", ("constraint",))
# -- observability substrate (ISSUE 9): the flight recorder, the
# -- device-runtime telemetry, and the trace ring's drop accounting
FLIGHT_RECORDS = _c(
    "karpenter_tpu_flight_records_total",
    "Flight-recorder records written, by record kind (solve = one "
    "single-problem attempt, delta = an engaged delta pass, spec = an "
    "engaged speculative chunk-chain pass, batch = one fused solverd "
    "batch).", ("kind",))
TIMELINE_EVENTS = _c(
    "karpenter_tpu_timeline_events_total",
    "Timeline-recorder events written, by event kind (store.<kind>.<op> "
    "informer-cache observations plus the semantic drive kinds from "
    "timeline/events.py — spot.reclaim, price.refresh, fault.inject, "
    "gang/priority arrival markers).", ("kind",))
SOLVER_RETRACES = _c(
    "karpenter_tpu_solver_retraces_total",
    "Kernel-body retraces (each is the only event that can trigger an "
    "XLA compile), by padded shape bucket. Post-warmup steady state "
    "must hold this flat — a climbing series means a padding-bucket "
    "cliff the warm-up lattice missed.", ("bucket",))
SOLVER_DEVICE_MEMORY_PEAK = _g(
    "karpenter_tpu_solver_device_memory_peak_bytes",
    "Peak device-memory bytes in use, sampled after each solve "
    "(PJRT memory_stats; 0 when the backend does not report — the "
    "XLA:CPU emulation path).")
SOLVER_DONATED_SLOTS = _g(
    "karpenter_tpu_solver_donated_slots_in_use",
    "Donated upload slots currently holding a live (undeleted) device "
    "buffer in the pipelined executor's double-buffer rotation.")
TRACE_SPANS_DROPPED = _c(
    "karpenter_tpu_trace_spans_dropped_total",
    "Spans evicted from the trace collector's bounded buffers (oldest "
    "finished trace pushed out of the ring, an orphaned in-progress "
    "trace evicted, or a pathological trace hitting the per-trace span "
    "cap) — the visibility half of the ring's silent-eviction bargain.")
SOLVER_ORACLE_BACKSTOP = _c(
    "karpenter_tpu_solver_oracle_backstop_total",
    "Solves where the full-oracle backstop beat the decomposed paths "
    "under a binding pool limit.")
# -- cost & efficiency observability (ISSUE 14): the objective itself —
# -- fleet $/hr, savings realized by disruption, how far packing sits
# -- from the allocatable envelope, and the live solver-vs-oracle audit
FLEET_HOURLY_COST = _g(
    "karpenter_tpu_fleet_hourly_cost",
    "Fleet spend in $/hr by nodepool and capacity type, summed over the "
    "cluster's live nodes' offering prices (utils/ledger.py "
    "update_fleet_metrics; refreshed by the provisioning pass when the "
    "cluster changed, with a 30 s staleness bound). The "
    "fleet total is the sum over all series — the exported form of the "
    "objective the solver minimizes.", ("pool", "capacity_type"))
DISRUPTION_SAVINGS = _c(
    "karpenter_tpu_disruption_savings_dollars_total",
    "Cumulative $/hr of fleet cost removed by disruption decisions, by "
    "method (emptiness/multi_node/single_node; drift replacements are "
    "spec-motivated, not cost-motivated, and never count): sum of "
    "retired "
    "candidate prices minus the replacement price, counted at decision "
    "time (the same floats the acceptance check compares to IEEE-hex "
    "exactness).", ("method",))
PACKING_EFFICIENCY = _g(
    "karpenter_tpu_packing_efficiency_ratio",
    "Per-nodepool packing efficiency by resource: sum of resident pod "
    "requests over sum of node allocatable (1.0 = perfectly packed; "
    "only resources with nonzero allocatable export a series).",
    ("pool", "resource"))
FLEET_PACKING_EFFICIENCY = _g(
    "karpenter_tpu_fleet_packing_efficiency_ratio",
    "Fleet-wide packing efficiency by resource (requested over "
    "allocatable across every live node).", ("resource",))
STRANDED_CAPACITY = _g(
    "karpenter_tpu_stranded_capacity_units",
    "Allocatable-minus-requested units sitting idle on live nodes, by "
    "nodepool and resource (solver units: millicores, MiB, counts) — "
    "the capacity being paid for but not requested, i.e. the "
    "consolidation opportunity in resource terms.", ("pool", "resource"))
FLEET_EFFICIENCY_BOUND = _g(
    "karpenter_tpu_fleet_efficiency_lower_bound_ratio",
    "Greedy cost lower bound over actual fleet $/hr: total pod requests "
    "priced at the cheapest feasible $/resource-unit across the "
    "catalog, divided by the real fleet cost (<= 1.0; 1.0 means spend "
    "is at the naive bound). Deliberately a CHEAP bound — the seam the "
    "relaxed-LP scoring from the convex-optimization line of work "
    "replaces with a tight one (docs/observability.md).")
LEDGER_RECORDS = _c(
    "karpenter_tpu_ledger_records_total",
    "Decision-ledger records written (utils/ledger.py), by decision "
    "source (provisioning/disruption/drift/expiration/interruption/"
    "termination).", ("source",))
SOLVER_AUDIT = _c(
    "karpenter_tpu_solver_audit_total",
    "Shadow-audit verdicts over sampled production solves "
    "(solver/audit.py, KARPENTER_TPU_AUDIT): match = bit-exact oracle "
    "parity, improved = the solver beat the oracle's cost/placement, "
    "diverged = the solver answered worse than the oracle or a delta "
    "pass failed its full re-solve parity (auto-captured for "
    "kt_replay), dropped = sampler backlog full, error = the "
    "verification itself failed.", ("verdict",))
# per-instance-type catalog gauges (reference:
# pkg/providers/instancetype/instancetype.go:156-161,302-311 + metrics.go)
INSTANCE_TYPE_CPU = _g(
    "karpenter_cloudprovider_instance_type_cpu_cores",
    "vCPUs per instance type.", ("instance_type",))
INSTANCE_TYPE_MEMORY = _g(
    "karpenter_cloudprovider_instance_type_memory_bytes",
    "Memory per instance type.", ("instance_type",))
INSTANCE_TYPE_OFFERING_PRICE = _g(
    "karpenter_cloudprovider_instance_type_offering_price_estimate",
    "Last known price per offering.",
    ("instance_type", "zone", "capacity_type"))
INSTANCE_TYPE_OFFERING_AVAILABLE = _g(
    "karpenter_cloudprovider_instance_type_offering_available",
    "Offering availability (0 = ICE-blocked).",
    ("instance_type", "zone", "capacity_type"))


class DecoratedCloudProvider:
    """metrics.Decorate analogue (cmd/controller/main.go:43): wraps every
    public CloudProvider method with duration + error counters. Methods are
    wrapped once at construction so repeated attribute reads return the same
    callable with no per-call allocation."""

    _METHODS = ("create", "delete", "get", "list_instances",
                "get_instance_types", "is_drifted", "live")

    def __init__(self, inner):
        self._inner = inner
        for name in self._METHODS:
            setattr(self, name, self._wrap(name, getattr(inner, name)))

    @staticmethod
    def _wrap(name, fn):
        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            except Exception:
                CLOUDPROVIDER_ERRORS.inc(method=name)
                raise
            finally:
                CLOUDPROVIDER_DURATION.observe(
                    time.perf_counter() - t0, method=name)

        return wrapped

    def __getattr__(self, name):
        return getattr(self._inner, name)
