"""Canonical `KARPENTER_TPU_*` knob grammar (ISSUE 12).

Every boolean knob in this codebase is parsed HERE, through
:func:`env_bool`, so on/off synonyms are symmetric by construction:
``1/true/yes/on`` enable, ``0/false/no/off`` disable, anything else —
including the empty string — degrades to the knob's documented default
(the MESH/DELTA discipline: a typo is a no-op, never a crash and never
a silent enable).  Before this module, four gates parsed truthiness by
hand and disagreed: ``KARPENTER_TPU_FORCE_CPU=0`` *forced CPU* (bare
truthiness), ``KARPENTER_TPU_TRACE=on`` did nothing (on-set missing
``on``), ``KARPENTER_TPU_WARMUP=off`` worked but ``=no`` enabled a
compile storm.  kt-lint's `env-knob` rule now fails any boolean knob
read that bypasses this function (hack/analyze/rules/env_knobs.py).

Non-boolean shared knobs with more than one consumer live here too
(:func:`bind_host`), so each knob keeps exactly one parsing owner.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

# the symmetric synonym sets — the contract docs/operations.md documents
ON_WORDS = ("1", "true", "yes", "on")
OFF_WORDS = ("0", "false", "no", "off")


def env_bool(name: str, default: bool = False,
             environ: Optional[Mapping[str, str]] = None) -> bool:
    """Parse a boolean `KARPENTER_TPU_*` knob with the canonical
    symmetric grammar.  Unset, empty, or malformed values return
    `default` — rollback knobs must degrade to the configured behavior,
    never flip it on a typo."""
    env = os.environ if environ is None else environ
    raw = env.get(name)
    if raw is None:
        return default
    val = raw.strip().lower()
    if val in ON_WORDS:
        return True
    if val in OFF_WORDS:
        return False
    return default


def gang_enabled() -> bool:
    """`KARPENTER_TPU_GANG`: the gang-scheduling rollback lever
    (default on).  Off, gang annotations are inert — members schedule
    as ordinary independent pods (no atomicity, no adjacency).  Parsed
    here (not in the scheduling layer) because BOTH the jax-free
    oracle/model layer and the solver read it, and each knob keeps
    exactly one grammar owner."""
    return env_bool("KARPENTER_TPU_GANG", default=True)


def priority_enabled() -> bool:
    """`KARPENTER_TPU_PRIORITY`: the priority-scheduling rollback lever
    (default on).  Off, priority classes and the `karpenter.tpu/priority`
    annotation are inert — pods keep their spec `priority` field in the
    scheduling key (pre-existing behavior) but no band ordering, no
    preemption planning, and no PriorityBandExhausted reclassification
    happen.  Parsed here because the jax-free model/oracle layer, the
    solver, and the preemption controller all read it, and each knob
    keeps exactly one grammar owner.  (The service admission-rank knob
    that previously used this name is now
    `KARPENTER_TPU_SERVICE_PRIORITY` — operator/options.py.)"""
    return env_bool("KARPENTER_TPU_PRIORITY", default=True)


def spot_risk_enabled() -> bool:
    """`KARPENTER_TPU_SPOT_RISK`: the spot-risk-weighted objective mode
    (default off).  On, winner selection in BOTH engines ranks columns
    by interruption-risk-adjusted effective price
    (scheduling/risk.py) instead of pure price; claim prices stay the
    REAL offering prices.  One grammar owner: encode, decode, and the
    oracle all resolve the mode through this function."""
    return env_bool("KARPENTER_TPU_SPOT_RISK", default=False)


def bind_host() -> str:
    """`KARPENTER_TPU_BIND_HOST`: the metrics/health/probe bind address
    (default loopback; `0.0.0.0` in containers).  Shared by the
    operator's debug server and the supervisor's probe listener — one
    parsing owner so the two can never read different defaults."""
    return os.environ.get("KARPENTER_TPU_BIND_HOST", "127.0.0.1")
