"""Cloud error taxonomy — classification driving retry/ICE behavior.

Mirrors pkg/errors/errors.go:57-100: IsNotFound (delete of a gone resource
is success), IsUnfulfillableCapacity (the ICE code list — feeds the
unavailable-offerings cache instead of failing the claim), and
IsLaunchTemplateNotFound (invalidate cache + retry once).
"""

from __future__ import annotations


def is_not_found(err: BaseException) -> bool:
    from karpenter_tpu.providers.fake_cloud import CloudAPIError
    return isinstance(err, CloudAPIError) and "not found" in str(err).lower()


def is_unfulfillable_capacity(err: BaseException) -> bool:
    """The insufficient-capacity error class: retry in a different pool,
    never fail provisioning outright (errors.go ICE code list)."""
    from karpenter_tpu.cloudprovider.provider import InsufficientCapacity
    return isinstance(err, InsufficientCapacity)


def is_launch_template_not_found(err: BaseException) -> bool:
    from karpenter_tpu.providers.fake_cloud import LaunchTemplateNotFound
    return isinstance(err, LaunchTemplateNotFound)


def is_retryable(err: BaseException) -> bool:
    """Transient cloud unavailability: keep the claim and retry the next
    reconcile (the liveness/backoff path, SURVEY §5 failure detection)."""
    from karpenter_tpu.providers.fake_cloud import CloudAPIError
    return (isinstance(err, CloudAPIError)
            and not is_not_found(err)
            and not is_launch_template_not_found(err))
