"""End-to-end span tracing across the provisioning pipeline.

The reference leans on pprof + per-controller metrics to attribute control
-loop latency (settings.md ENABLE_PROFILING); our budget analysis needs
per-REQUEST causality on top of the metric totals — which reconcile pass
paid the 50 ms device segment, and what its parent was. This module is
the in-process analogue of a W3C-trace-context tracer reduced to the
slice the operator needs:

  - `span(name, **attrs)`    context manager; nests via a thread-local
                             stack, starts a new trace at the root
  - `record_span(...)`       retroactive child span for already-timed
                             intervals (the solver's phase stamps)
  - `inject()` / `extract()` a `traceparent`-style field carried in the
                             solverd RPC body so remote-solver spans
                             stitch into the caller's trace
  - `chrome_trace()`         Chrome trace-event JSON (loadable in
                             Perfetto / chrome://tracing) of the bounded
                             ring buffer of completed traces

Gating mirrors `utils/profiling.trace_solve`: tracing is off unless
KARPENTER_TPU_TRACE is truthy (or a remote context was extracted on this
thread), and the disabled path is one thread-local context lookup plus
one env dict get per span — nothing rides the 200 ms solve budget.

Bounds: completed traces live in a ring buffer of KARPENTER_TPU_TRACE_BUFFER
traces (default 64); in-progress traces are capped (oldest evicted) so an
orphaned context can never grow memory; spans per trace are capped so a
pathological loop cannot balloon one trace entry.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

_ENV_GATE = "KARPENTER_TPU_TRACE"
_ENV_BUFFER = "KARPENTER_TPU_TRACE_BUFFER"

_MAX_LIVE_TRACES = 256     # orphan bound: oldest in-progress trace evicted
_MAX_SPANS_PER_TRACE = 4096

_enabled_override: Optional[bool] = None
_tl = threading.local()


def _count_dropped(n: int) -> None:
    """Eviction accounting (`karpenter_tpu_trace_spans_dropped_total`):
    the bounded buffers drop spans by design, and the drop count is what
    tells an operator the ring was too small for the trace volume —
    surfaced by `GET /debug/traces` alongside the export.  Imported
    lazily so tracing stays importable from the metrics module's own
    test fixtures without a cycle."""
    from karpenter_tpu.utils import metrics
    metrics.TRACE_SPANS_DROPPED.inc(n)


def tracing_enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    from karpenter_tpu.utils.knobs import env_bool
    return env_bool(_ENV_GATE)


def set_enabled(value: Optional[bool]) -> None:
    """Programmatic override, `None` defers back to the env gate — tests
    and embedding processes; the operator leaves it to the environment."""
    global _enabled_override
    _enabled_override = value


def _stack() -> list:
    st = getattr(_tl, "stack", None)
    if st is None:
        st = _tl.stack = []
    return st


def _active() -> bool:
    """True when a span on THIS thread should record: an enclosing
    context exists (local span or extracted remote parent) or the global
    gate is on. The disabled fast path is the `getattr` + one env get."""
    return bool(getattr(_tl, "stack", None)) or tracing_enabled()


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "duration", "attrs", "thread")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, start: float, duration: float, attrs: dict,
                 thread: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start          # wall-clock epoch seconds
        self.duration = duration    # seconds
        self.attrs = attrs
        self.thread = thread

    def to_dict(self) -> dict:
        """Pickle/JSON-stable wire form (the solverd response rides this)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start": self.start, "duration": self.duration,
                "attrs": dict(self.attrs), "thread": self.thread}

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(d["trace_id"], d["span_id"], d.get("parent_id"),
                   d["name"], d["start"], d["duration"],
                   dict(d.get("attrs") or {}), d.get("thread", ""))


class _Collector:
    """Completed spans of in-progress traces + a bounded ring buffer of
    finished traces."""

    def __init__(self):
        self._lock = threading.Lock()
        self._live: "OrderedDict[str, List[Span]]" = OrderedDict()
        self._finished: deque = deque(maxlen=self._buffer_size())

    @staticmethod
    def _buffer_size() -> int:
        try:
            return max(1, int(os.environ.get(_ENV_BUFFER, "64")))
        except ValueError:
            return 64

    def add(self, span: Span, finalize: bool = False) -> None:
        dropped = 0
        try:
            with self._lock:
                spans = self._live.get(span.trace_id)
                if spans is None:
                    # a late span for an already-finished trace (an
                    # async batcher window closing after the root) joins
                    # its entry
                    late = next((fspans for tid, fspans in self._finished
                                 if tid == span.trace_id), None)
                    if late is not None:
                        if len(late) < _MAX_SPANS_PER_TRACE:
                            late.append(span)
                        else:
                            dropped += 1
                        return
                    spans = self._live[span.trace_id] = []
                    while len(self._live) > _MAX_LIVE_TRACES:
                        _, orphaned = self._live.popitem(last=False)
                        dropped += len(orphaned)
                if len(spans) < _MAX_SPANS_PER_TRACE:
                    spans.append(span)
                else:
                    dropped += 1
                if finalize:
                    done = self._live.pop(span.trace_id, None)
                    if done is not None:
                        if len(self._finished) == self._finished.maxlen:
                            # the deque silently evicts its oldest trace
                            # to make room — those spans are drops too
                            dropped += len(self._finished[0][1])
                        self._finished.append((span.trace_id, done))
        finally:
            if dropped:
                _count_dropped(dropped)

    def take(self, trace_id: str) -> List[Span]:
        """Remove and return an in-progress trace's spans (the extract
        side of the RPC boundary ships them back to the caller)."""
        with self._lock:
            return self._live.pop(trace_id, [])

    def finished(self, trace_id: Optional[str] = None) -> List[tuple]:
        with self._lock:
            out = [(tid, list(spans)) for tid, spans in self._finished]
        if trace_id is not None:
            out = [e for e in out if e[0] == trace_id]
        return out

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._finished = deque(maxlen=self._buffer_size())


_collector = _Collector()


def _new_trace_id() -> str:
    return uuid.uuid4().hex              # 32 hex chars, traceparent-shaped


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]         # 16 hex chars


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _SpanCm:
    __slots__ = ("name", "parent", "span")

    def __init__(self, name: str, parent: Optional[Tuple[str, str]],
                 attrs: dict):
        self.name = name
        self.parent = parent
        self.span = Span("", _new_span_id(), None, name, 0.0, 0.0, attrs,
                         threading.current_thread().name)

    def __enter__(self) -> Span:
        sp = self.span
        st = _stack()
        parent = self.parent or (st[-1] if st else None)
        if parent is None:
            sp.trace_id = _new_trace_id()
        else:
            sp.trace_id, sp.parent_id = parent
        sp.start = time.time()
        st.append((sp.trace_id, sp.span_id))
        return sp

    def __exit__(self, *exc):
        sp = self.span
        sp.duration = time.time() - sp.start
        st = _stack()
        if st and st[-1] == (sp.trace_id, sp.span_id):
            st.pop()
        # a trace completes when its ROOT span ends; spans parented on a
        # captured/remote context never finalize here (extract() or the
        # owning thread's root does)
        _collector.add(sp, finalize=(sp.parent_id is None
                                     and self.parent is None))
        return False


def span(name: str, parent: Optional[Tuple[str, str]] = None, **attrs):
    """Context manager for one span. `parent` overrides the thread-local
    context with a captured `(trace_id, span_id)` (cross-thread stitching,
    e.g. the batcher's worker). Yields the Span so callers can add attrs
    discovered mid-flight (`sp.attrs["path"] = ...`)."""
    if parent is None and not _active():
        return _NOOP
    return _SpanCm(name, parent, attrs)


def child_span(name: str, **attrs):
    """A span only when a trace is already active on this thread — I/O
    annotations (store requests, batcher windows) enrich traces but never
    start one of their own."""
    if not getattr(_tl, "stack", None):
        return _NOOP
    return _SpanCm(name, None, attrs)


def record_span(name: str, start: float, duration: float, **attrs) -> None:
    """Retroactive completed child of the current context — for intervals
    the caller already timed (the solver's per-phase perf stamps)."""
    st = getattr(_tl, "stack", None)
    if not st:
        return
    trace_id, parent_id = st[-1]
    _collector.add(Span(trace_id, _new_span_id(), parent_id, name, start,
                        duration, attrs, threading.current_thread().name))


def current() -> Optional[Tuple[str, str]]:
    """Capture the active `(trace_id, span_id)` for cross-thread or
    cross-process propagation; None when no trace is active."""
    st = getattr(_tl, "stack", None)
    return st[-1] if st else None


def current_trace_id() -> Optional[str]:
    st = getattr(_tl, "stack", None)
    return st[-1][0] if st else None


# -- traceparent-style propagation (W3C trace-context shaped) -------------
def inject() -> Optional[str]:
    """`00-<trace_id>-<span_id>-01` for the active span, else None. Rides
    the solverd schedule body so the daemon's spans join this trace."""
    ctx = current()
    if ctx is None:
        return None
    return f"00-{ctx[0]}-{ctx[1]}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    if not header or not isinstance(header, str):
        return None
    parts = header.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


class _RemoteTrace:
    """Extracted remote context: spans opened inside the `with` block are
    children of the caller's span; on exit they are collected into
    `.spans` for the response to carry back (they belong to the CALLER's
    trace, not this process's ring buffer)."""

    __slots__ = ("ctx", "spans")

    def __init__(self, ctx: Optional[Tuple[str, str]]):
        self.ctx = ctx
        self.spans: List[Span] = []

    def __enter__(self) -> "_RemoteTrace":
        if self.ctx is not None:
            _stack().append(self.ctx)
        return self

    def __exit__(self, *exc):
        if self.ctx is not None:
            st = _stack()
            if st and st[-1] == self.ctx:
                st.pop()
            self.spans = _collector.take(self.ctx[0])
        return False


def extract(header: Optional[str]) -> _RemoteTrace:
    """Context manager adopting a remote `traceparent`; inert (and free)
    when the header is absent or malformed. The remote side records even
    when its own env gate is off — the caller made the gating decision."""
    return _RemoteTrace(parse_traceparent(header))


def adopt(span_dicts: List[dict]) -> None:
    """Merge spans shipped back across the RPC boundary into the local
    collector. They already carry this process's trace ids (the caller
    injected them), so they stitch under the still-open local trace."""
    for d in span_dicts:
        try:
            _collector.add(Span.from_dict(d))
        except (KeyError, TypeError):
            continue  # a malformed remote span must not poison the trace


# -- export ----------------------------------------------------------------
def finished_traces(trace_id: Optional[str] = None) -> List[tuple]:
    """[(trace_id, [Span, ...]), ...] — most recent last."""
    return _collector.finished(trace_id)


def chrome_trace(trace_id: Optional[str] = None,
                 limit: Optional[int] = None) -> dict:
    """Chrome trace-event JSON (the `traceEvents` array format) of the
    completed-trace ring buffer, loadable in Perfetto / chrome://tracing.
    Spans become complete ("X") events; each trace maps to one pid so
    Perfetto groups its spans, threads map to tids within it.  `limit`
    keeps only the most recent N traces (the `?limit=` parameter on
    `GET /debug/traces` — a large ring must not dump unbounded JSON);
    `otherData.spansDropped` carries the collector's eviction counter so
    a truncated-looking trace is distinguishable from a dropped one."""
    traces = finished_traces(trace_id)
    if limit is not None and limit >= 0:
        # slice from the front: traces[-0:] would be the WHOLE list, the
        # exact opposite of the cap ?limit=0 asks for
        traces = traces[len(traces) - limit:] if limit else []
    events: List[dict] = []
    for pid, (tid_, spans) in enumerate(traces, start=1):
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"trace {tid_[:16]}"},
        })
        threads: Dict[str, int] = {}
        for sp in spans:
            tid = threads.setdefault(sp.thread, len(threads) + 1)
            events.append({
                "name": sp.name,
                "ph": "X",
                "ts": sp.start * 1e6,        # microseconds
                "dur": max(sp.duration, 0.0) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {"trace_id": sp.trace_id, "span_id": sp.span_id,
                         "parent_id": sp.parent_id, **sp.attrs},
            })
    from karpenter_tpu.utils import metrics
    return {"displayTimeUnit": "ms", "traceEvents": events,
            "otherData": {
                "spansDropped": int(metrics.TRACE_SPANS_DROPPED.value()),
                "tracesReturned": len(traces)}}


def reset() -> None:
    """Clear all collected state (tests)."""
    _collector.reset()
    st = getattr(_tl, "stack", None)
    if st:
        del st[:]
