"""Structured logging with change-gated noise suppression.

The reference logs through zap via controller-runtime's `log.FromContext`
and gates repetitive provider logs behind `pretty.ChangeMonitor`
(/root/reference/pkg/providers/instancetype/instancetype.go:151-153 — the
instance-type count is logged only when it CHANGES, not every 5-minute
refresh). Same shape here: logfmt lines on stderr, level from LOG_LEVEL,
and a ChangeMonitor for polling loops.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}


def _configured_level() -> int:
    return _LEVELS.get(os.environ.get("LOG_LEVEL", "info").strip().lower(), 20)


def _fmt_value(v: object) -> str:
    s = str(v)
    # newlines would split one logfmt record across lines (multi-line
    # exception messages are common kv values)
    s = s.replace("\n", "\\n").replace("\r", "\\r")
    if any(c in s for c in ' "='):
        s = '"' + s.replace('"', '\\"') + '"'
    return s


class Logger:
    """A named logfmt logger: `log.info("msg", pods=3, pool="default")` →
    `ts=... level=info logger=provisioner msg="..." pods=3 pool=default`.
    """

    def __init__(self, name: str, stream=None):
        self.name = name
        self.stream = stream

    def _emit(self, level: str, msg: str, kv: dict) -> None:
        if _LEVELS[level] < _configured_level():
            return
        # UTC with millisecond precision: span start/end times are wall
        # clock (utils/tracing), so log lines must carry enough timestamp
        # to line up against them — local-time whole seconds can't
        now = time.time()
        parts = [
            f"ts={time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(now))}"
            f".{int(now * 1000) % 1000:03d}Z",
            f"level={level}",
            f"logger={self.name}",
            f"msg={_fmt_value(msg)}",
        ]
        parts += [f"{k}={_fmt_value(v)}" for k, v in kv.items()]
        print(" ".join(parts), file=self.stream or sys.stderr, flush=True)

    def debug(self, msg: str, **kv) -> None:
        self._emit("debug", msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit("info", msg, kv)

    def warn(self, msg: str, **kv) -> None:
        self._emit("warn", msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit("error", msg, kv)


_loggers: dict = {}
_lock = threading.Lock()


def get_logger(name: str) -> Logger:
    with _lock:
        lg = _loggers.get(name)
        if lg is None:
            lg = _loggers[name] = Logger(name)
        return lg


class ChangeMonitor:
    """Noise gate for polling loops: `has_changed(key, value)` is True only
    when `value` differs from the last one seen for `key` (or the entry
    aged out). Mirrors the reference's pretty.ChangeMonitor — refresh
    controllers log state only on change, not on every poll."""

    def __init__(self, ttl: float = 24 * 3600.0, now=time.monotonic):
        self.ttl = ttl
        self._now = now
        self._seen: dict = {}
        self._lock = threading.Lock()
        self._next_sweep = now() + ttl

    def has_changed(self, key: str, value: object) -> bool:
        now = self._now()
        with self._lock:
            if now >= self._next_sweep:
                # opportunistic expiry sweep: per-KEY polling loops (one
                # entry per node name, pod uid, ...) otherwise grow _seen
                # forever in a long-running operator — expired entries
                # would re-log anyway, so dropping them changes nothing
                self._seen = {k: e for k, e in self._seen.items()
                              if now - e[1] < self.ttl}
                self._next_sweep = now + self.ttl
            entry = self._seen.get(key)
            if entry is not None:
                last_value, stamp = entry
                if last_value == value and now - stamp < self.ttl:
                    return False
            self._seen[key] = (value, now)
            return True
