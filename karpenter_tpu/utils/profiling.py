"""Profiling gate — the reference exposes pprof behind ENABLE_PROFILING
(website/.../settings.md:23); our hot path is XLA programs, so the
equivalent is the JAX profiler (SURVEY §5: "JAX profiler + XLA traces on
the solver"), gated the same way:

  ENABLE_PROFILING=true              start the profiler server (:9999 or
                                     KARPENTER_TPU_PROFILE_PORT) at boot —
                                     attach TensorBoard / xprof on demand
  KARPENTER_TPU_PROFILE_DIR=<dir>    additionally trace every solve into
                                     <dir> (one trace per solve, for
                                     offline xprof analysis)

Disabled (the default), `trace_solve` is a no-op context manager with one
dict lookup of overhead — nothing rides the 200 ms budget.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

_server_started = False


def profiling_enabled() -> bool:
    return os.environ.get("ENABLE_PROFILING", "").strip().lower() in (
        "1", "true", "yes")


def maybe_start_server(log=None) -> Optional[int]:
    """Start the JAX profiler server once, when ENABLE_PROFILING is set.
    Returns the port or None."""
    global _server_started
    if not profiling_enabled() or _server_started:
        return None
    port = int(os.environ.get("KARPENTER_TPU_PROFILE_PORT", "9999"))
    import jax
    jax.profiler.start_server(port)
    _server_started = True
    if log is not None:
        log(f"jax profiler server on :{port}")
    return port


@contextlib.contextmanager
def trace_solve(name: str = "solve"):
    """Trace one solve into KARPENTER_TPU_PROFILE_DIR when set; otherwise
    a no-op. The annotation names the region in xprof."""
    trace_dir = os.environ.get("KARPENTER_TPU_PROFILE_DIR")
    if not trace_dir:
        yield
        return
    import jax
    with jax.profiler.trace(trace_dir):
        with jax.profiler.TraceAnnotation(name):
            yield
