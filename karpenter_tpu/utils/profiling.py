"""Profiling gate — the reference exposes pprof behind ENABLE_PROFILING
(website/.../settings.md:23); our hot path is XLA programs, so the
equivalent is the JAX profiler (SURVEY §5: "JAX profiler + XLA traces on
the solver"), gated the same way:

  ENABLE_PROFILING=true              start the profiler server (:9999 or
                                     KARPENTER_TPU_PROFILE_PORT) at boot —
                                     attach TensorBoard / xprof on demand
  KARPENTER_TPU_PROFILE_DIR=<dir>    additionally trace every solve into
                                     <dir> (one trace per solve, for
                                     offline xprof analysis)
  KARPENTER_TPU_PROFILE=<dir>|1      the one-knob spelling of the same
                                     per-solve trace hook (ISSUE 9): a
                                     directory value traces there; a bare
                                     truthy value traces into
                                     KARPENTER_TPU_PROFILE_DIR or
                                     ./profiles.  Opt-in — the recorder
                                     and metrics stay the always-on layer;
                                     this hook is the heavyweight XLA
                                     deep-dive.

Disabled (the default), `trace_solve` is a no-op context manager with one
dict lookup of overhead — nothing rides the 200 ms budget.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

_server_started = False


def profiling_enabled() -> bool:
    return os.environ.get("ENABLE_PROFILING", "").strip().lower() in (
        "1", "true", "yes")


def maybe_start_server(log=None) -> Optional[int]:
    """Start the JAX profiler server once, when ENABLE_PROFILING is set.
    Returns the port or None."""
    global _server_started
    if not profiling_enabled() or _server_started:
        return None
    port = int(os.environ.get("KARPENTER_TPU_PROFILE_PORT", "9999"))
    import jax
    jax.profiler.start_server(port)
    _server_started = True
    if log is not None:
        log(f"jax profiler server on :{port}")
    return port


def device_memory_peak() -> int:
    """Peak device-memory bytes in use across local devices (PJRT
    `memory_stats`), the per-solve watermark the flight recorder and
    `karpenter_tpu_solver_device_memory_peak_bytes` sample.  0 when the
    backend does not report (the XLA:CPU emulation path) — absence of
    telemetry must read as zero, never raise into a solve."""
    try:
        import jax
        peak = 0
        for d in jax.local_devices():
            ms = d.memory_stats()
            if ms:
                peak = max(peak, int(ms.get(
                    "peak_bytes_in_use", ms.get("bytes_in_use", 0))))
        return peak
    except Exception:  # noqa: BLE001 — telemetry, not control flow
        return 0


def profile_trace_dir() -> Optional[str]:
    """Resolve the per-solve trace destination: KARPENTER_TPU_PROFILE
    (a directory, or a bare truthy value deferring to
    KARPENTER_TPU_PROFILE_DIR / ./profiles), else KARPENTER_TPU_PROFILE_DIR
    alone.  None = the hook is off (the default)."""
    raw = os.environ.get("KARPENTER_TPU_PROFILE", "").strip()
    legacy = os.environ.get("KARPENTER_TPU_PROFILE_DIR")
    if not raw or raw.lower() in ("0", "false", "off", "no"):
        return legacy or None
    if raw.lower() in ("1", "true", "yes", "on"):
        return legacy or "profiles"
    return raw  # a directory path


@contextlib.contextmanager
def trace_solve(name: str = "solve"):
    """Trace one solve into the resolved profile directory when the
    KARPENTER_TPU_PROFILE / KARPENTER_TPU_PROFILE_DIR hook is armed;
    otherwise a no-op. The annotation names the region in xprof."""
    trace_dir = profile_trace_dir()
    if not trace_dir:
        yield
        return
    import jax
    with jax.profiler.trace(trace_dir):
        with jax.profiler.TraceAnnotation(name):
            yield
