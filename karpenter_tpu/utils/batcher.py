"""Windowed request-coalescing batcher.

The reference amortizes cloud-API round trips by coalescing concurrent
identical-shaped requests into one batched call behind a small idle/max
window (pkg/batcher/batcher.go:61-183): callers block on Add() while a
trigger goroutine waits for the request stream to go idle (or the window /
size cap to hit), then fans the whole bucket out as one API call and
distributes per-item results back to the callers. Requests are bucketed by
a hash of their non-batchable fields (DefaultHasher, batcher.go:119-125) so
only compatible requests share a call.

This is the same machinery we use to amortize the host↔TPU solver hop: many
concurrent Schedule() calls coalesce into one padded pods×types tensor batch
(SURVEY §2.3).

Per-API window constants mirror the reference:
  create_fleet        idle 35 ms / max 1 s / 1000 items (createfleet.go:35-37)
  describe_instances  idle 100 ms / max 1 s / 500 items (describeinstances.go:39-41)
  terminate_instances idle 100 ms / max 1 s / 500 items (terminateinstances.go:38-40)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Generic, Hashable, List, Optional, TypeVar

from karpenter_tpu.utils import metrics, tracing

T = TypeVar("T")  # request item
U = TypeVar("U")  # per-item result

# (idle window s, max window s, max items) — reference constants
CREATE_FLEET_WINDOW = (0.035, 1.0, 1000)
DESCRIBE_INSTANCES_WINDOW = (0.100, 1.0, 500)
TERMINATE_INSTANCES_WINDOW = (0.100, 1.0, 500)


@dataclass
class _Pending(Generic[T, U]):
    request: T
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[U] = None
    error: Optional[BaseException] = None
    # submitter's trace context, captured at submit() time: the window
    # executes on a worker thread with no thread-local trace of its own,
    # so the execute span stitches under the first traced submitter
    trace_ctx: Optional[tuple] = None


class _Bucket(Generic[T, U]):
    def __init__(self) -> None:
        self.items: List[_Pending[T, U]] = []
        self.first_ts: float = 0.0
        self.last_ts: float = 0.0
        self.worker: Optional[threading.Thread] = None


class Batcher(Generic[T, U]):
    """Coalesces concurrent ``add()`` calls into batched executor calls.

    ``executor(requests) -> results`` receives the drained bucket and must
    return one result per request, in order (or raise — the error is
    re-raised in every blocked caller, matching the reference's behavior of
    failing the whole batch, batcher.go:166-176).

    ``hasher(request)`` buckets requests; only same-hash requests share a
    call (non-batchable fields — e.g. launch-template config — go in the
    hash; per-item fields — e.g. instance ids — are the batch payload).
    """

    def __init__(
        self,
        executor: Callable[[List[T]], List[U]],
        idle_s: float = 0.1,
        max_s: float = 1.0,
        max_items: int = 500,
        hasher: Optional[Callable[[T], Hashable]] = None,
        name: str = "batcher",
    ):
        self.executor = executor
        self.idle_s = idle_s
        self.max_s = max_s
        self.max_items = max_items
        self.hasher = hasher or (lambda _req: 0)
        self.name = name
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._buckets: Dict[Hashable, _Bucket[T, U]] = {}
        # observability (role of pkg/batcher/metrics.go)
        self.batches_executed = 0
        self.items_batched = 0
        self.batch_sizes: List[int] = []

    def add(self, request: T) -> U:
        """Block until the batch containing ``request`` executes; return this
        request's result (pkg/batcher/batcher.go:101-116)."""
        return self.wait(self.submit(request))

    def submit(self, request: T) -> "_Pending[T, U]":
        """Enqueue without blocking — lets one caller put many items into the
        same window before waiting (terminate_instances takes a list)."""
        pending: _Pending[T, U] = _Pending(request,
                                           trace_ctx=tracing.current())
        key = self.hasher(request)
        now = time.monotonic()
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None or bucket.worker is None:
                bucket = _Bucket()
                self._buckets[key] = bucket
                bucket.first_ts = now
                bucket.worker = threading.Thread(
                    target=self._run_window, args=(key, bucket), daemon=True)
                start_worker = True
            else:
                start_worker = False
            bucket.items.append(pending)
            bucket.last_ts = now
            if len(bucket.items) >= self.max_items:
                self._wake.notify_all()  # size cap: fire immediately
        if start_worker:
            bucket.worker.start()
        return pending

    def wait(self, pending: "_Pending[T, U]") -> U:
        pending.done.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result  # type: ignore[return-value]

    def _run_window(self, key: Hashable, bucket: _Bucket[T, U]) -> None:
        # wait for idle (no new adds for idle_s) or the max window / size cap
        with self._lock:
            while True:
                now = time.monotonic()
                idle_done = now - bucket.last_ts >= self.idle_s
                max_done = now - bucket.first_ts >= self.max_s
                full = len(bucket.items) >= self.max_items
                if idle_done or max_done or full:
                    # drain at most max_items — real APIs cap per-request
                    # item counts; late adds racing the size-cap notify stay
                    # queued for the next batch
                    items = bucket.items[:self.max_items]
                    bucket.items = bucket.items[self.max_items:]
                    if bucket.items:
                        bucket.first_ts = now
                        bucket.worker = threading.Thread(
                            target=self._run_window, args=(key, bucket),
                            daemon=True)
                        bucket.worker.start()
                    else:
                        bucket.worker = None
                        if self._buckets.get(key) is bucket:
                            del self._buckets[key]
                    break
                wait = min(self.idle_s - (now - bucket.last_ts),
                           self.max_s - (now - bucket.first_ts))
                self._wake.wait(timeout=max(wait, 0.001))
        self._execute(items)

    def _execute(self, items: List[_Pending[T, U]]) -> None:
        requests = [p.request for p in items]
        ctx = next((p.trace_ctx for p in items if p.trace_ctx), None)
        try:
            if ctx is not None:
                with tracing.span("batcher.execute", parent=ctx,
                                  batcher=self.name, items=len(items)):
                    results = self.executor(requests)
            else:
                results = self.executor(requests)
            if len(results) != len(requests):
                raise RuntimeError(
                    f"{self.name}: executor returned {len(results)} results "
                    f"for {len(requests)} requests")
        except BaseException as err:  # noqa: BLE001 — fail the whole batch
            for p in items:
                p.error = err
                p.done.set()
            return
        self.batches_executed += 1
        self.items_batched += len(items)
        self.batch_sizes.append(len(items))
        metrics.BATCHER_BATCH_SIZE.observe(len(items), batcher=self.name)
        for p, r in zip(items, results):
            p.result = r
            p.done.set()

    def flush(self) -> None:
        """Close every open window now (test/shutdown aid)."""
        with self._lock:
            for bucket in self._buckets.values():
                bucket.first_ts -= self.max_s
            self._wake.notify_all()
