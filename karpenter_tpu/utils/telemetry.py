"""Fleet telemetry aggregation (ISSUE 9 tentpole part 3).

The control plane is several processes — operator replicas, the solverd
supervisor, the kt_solverd worker — each with its own metrics registry
and flight-recorder ring.  This module is the merge point: every process
can produce a compact `local_snapshot()` of its observable state, other
in-process components (the supervisor) register themselves as snapshot
*sources*, the solverd worker's snapshot arrives through the stats RPC,
and `merge()` folds them into the ONE view `GET /debug/dashboard`
serves: solve rate, p50/p99 phase latencies, delta hit/fallback split,
queue depth, shed/retry/breaker/restart state, and the flight-recorder
tail — the aggregated-view half of the request-record + aggregated-view
split (the flight recorder is the request-record half).

Everything here is read-only over the metrics registry and best-effort:
a dashboard render must never throw into the operator's HTTP thread.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from karpenter_tpu.utils import metrics

_lock = threading.Lock()
_sources: Dict[str, Callable[[], dict]] = {}
# solve-rate window: (monotonic ts, total solves) of the previous
# snapshot, so successive dashboard scrapes see a rate, not a total
_rate_state = {"ts": None, "total": None}


def register_source(name: str, fn: Callable[[], dict]) -> None:
    """Register an in-process snapshot source (the solverd supervisor
    registers its restart/liveness state here on start()).  Last
    registration per name wins — a restarted component replaces its
    predecessor."""
    with _lock:
        _sources[name] = fn


def unregister_source(name: str, fn: Optional[Callable] = None) -> None:
    """Remove a source; with `fn` given, only when it is still the
    registered callable — a stopped component must not evict the
    replacement that took its name."""
    with _lock:
        if fn is None or _sources.get(name) is fn:
            _sources.pop(name, None)


def _series(metric) -> dict:
    """A labeled metric's samples as {label-values-joined: value};
    unlabeled metrics map the empty key.  Snapshot under the metric's
    own lock: a solve thread registering a first-time label key resizes
    the dict, and an unlocked iteration here would raise into the
    dashboard's HTTP thread."""
    vals = getattr(metric, "_values", None)
    if vals is None:
        return {}
    with metric._lock:
        items = sorted(vals.items())
    return {"/".join(k) if k else "": v for k, v in items}


def _quantile_upper(buckets, counts, total: int, q: float) -> float:
    """Histogram quantile as the upper bound of the first bucket whose
    cumulative count reaches q·total — the standard conservative read of
    a Prometheus-style histogram (exact values are gone; the bound is
    what dashboards alert on)."""
    need = q * total
    for b, c in zip(buckets, counts):
        if c >= need:
            return b
    return float("inf")


def phase_latency_summary() -> dict:
    """{phase/path: {count, p50_ms, p99_ms}} from the solver phase
    histogram — the per-request spans aggregated into the fleet view."""
    h = metrics.SOLVER_PHASE_DURATION
    out = {}
    with h._lock:  # same snapshot discipline as _series
        totals = sorted(h._totals.items())
        all_counts = {k: list(v) for k, v in h._counts.items()}
    for key, total in totals:
        counts = all_counts.get(key, [])
        out["/".join(key)] = {
            "count": total,
            "p50_ms": round(
                _quantile_upper(h.buckets, counts, total, 0.50) * 1e3, 3),
            "p99_ms": round(
                _quantile_upper(h.buckets, counts, total, 0.99) * 1e3, 3),
        }
    return out


def _explain_store_size() -> int:
    """Pods currently held by the explain store — guarded: telemetry
    must render even if the solver package is unimportable here."""
    try:
        from karpenter_tpu.solver import explain
        return explain.STORE.size()
    except Exception:  # noqa: BLE001 — best-effort, never the data path
        return 0


def _cost_section(ledger_tail: int = 8) -> dict:
    """This process's cost/efficiency observables (utils/ledger.py +
    the ISSUE 14 metric families), read-only over the registry."""
    try:
        from karpenter_tpu.utils import ledger
        tail = ledger.LEDGER.tail(ledger_tail)
    except Exception:  # noqa: BLE001 — best-effort, never the data path
        tail = []
    return {
        "fleet_hourly_cost": _series(metrics.FLEET_HOURLY_COST),
        "savings": _series(metrics.DISRUPTION_SAVINGS),
        "packing_efficiency": _series(metrics.FLEET_PACKING_EFFICIENCY),
        "stranded": _series(metrics.STRANDED_CAPACITY),
        "efficiency_lower_bound": metrics.FLEET_EFFICIENCY_BOUND.value(),
        "ledger_records": _series(metrics.LEDGER_RECORDS),
        "audit": _series(metrics.SOLVER_AUDIT),
        "ledger_tail": tail,
    }


def _timeline_section(tail: int = 8) -> dict:
    """This process's cluster-timeline observables (timeline/
    recorder.py): the per-kind event counters plus a short tail with
    trace/flight/ledger cross-links intact."""
    try:
        from karpenter_tpu import timeline
        return {
            "events": _series(metrics.TIMELINE_EVENTS),
            "last_seq": timeline.RECORDER.last_seq(),
            "tail": timeline.RECORDER.tail(tail),
        }
    except Exception:  # noqa: BLE001 — best-effort, never the data path
        return {"events": {}, "last_seq": None, "tail": []}


def local_snapshot(flight_tail: int = 16) -> dict:
    """This process's observable state: the compact dict every process
    role (operator, solverd backend, supervisor CLI) can produce and the
    dashboard merges."""
    from karpenter_tpu.utils import flightrecorder, tracing  # noqa: F401
    solves = _series(metrics.SOLVER_SOLVES)
    total = sum(solves.values())
    now = time.monotonic()
    rate = None
    with _lock:
        if _rate_state["ts"] is not None and now > _rate_state["ts"]:
            rate = max(0.0, (total - _rate_state["total"])
                       / (now - _rate_state["ts"]))
        _rate_state["ts"], _rate_state["total"] = now, total
    return {
        "ts": time.time(),
        "pid": os.getpid(),
        "queue_depth": metrics.SCHEDULING_QUEUE_DEPTH.value(),
        "solves": solves,
        "solves_total": total,
        "solve_rate_per_s": None if rate is None else round(rate, 3),
        "phase_latency_ms": phase_latency_summary(),
        "delta": {
            "passes": _series(metrics.SOLVER_DELTA_PASSES),
            "groups_reencoded":
                metrics.SOLVER_DELTA_GROUPS_REENCODED.value(),
            # event-driven incremental index (ISSUE 20): index-resolved
            # vs walk-resolved grouping passes, same counted discipline
            "incr_passes": _series(metrics.SOLVER_INCR_PASSES),
        },
        "service": {
            "retries": metrics.SERVICE_RETRIES.value(),
            "breaker_state": metrics.SERVICE_BREAKER_STATE.value(),
            "worker_restarts": metrics.SERVICE_WORKER_RESTARTS.value(),
        },
        # multi-tenant dispatch (ISSUE 11): per-tenant demand/queues/
        # sheds and the cross-tenant fusion counters — in the solverd
        # worker these are the live series; in other processes they stay
        # empty dicts and merge() skips them
        "tenants": {
            "queue_depth": _series(metrics.SERVICE_TENANT_QUEUE_DEPTH),
            "requests": _series(metrics.SERVICE_TENANT_REQUESTS),
            "shed": _series(metrics.SERVICE_TENANT_SHED),
            "fused_batches": _series(metrics.SERVICE_FUSED_BATCHES),
        },
        # placement provenance (ISSUE 13): per-reason unschedulable
        # verdicts, per-constraint elimination attribution, and the
        # explain store's reach — in the solverd worker the elimination
        # series is the live one (it rides the stats RPC to the
        # operator's dashboard merge); the verdict counter lives where
        # provisioning runs
        "placement": {
            "unschedulable": _series(metrics.UNSCHEDULABLE_PODS),
            "eliminations": _series(metrics.SOLVER_CONSTRAINT_ELIM),
            "explained_pods": _explain_store_size(),
        },
        # cost & efficiency (ISSUE 14): fleet spend, savings realized,
        # packing efficiency, the shadow-audit verdicts, and the
        # decision-ledger tail — the gauges live where the controllers
        # run (the operator); other process roles carry empty series and
        # merge() skips them.  Guarded like the explain-store read: a
        # telemetry snapshot must render even if the ledger module is
        # unimportable here.
        "cost": _cost_section(),
        "retraces": sum(_series(metrics.SOLVER_RETRACES).values()),
        "device_memory_peak_bytes":
            metrics.SOLVER_DEVICE_MEMORY_PEAK.value(),
        "donated_slots_in_use": metrics.SOLVER_DONATED_SLOTS.value(),
        "spans_dropped": metrics.TRACE_SPANS_DROPPED.value(),
        "flight_records": _series(metrics.FLIGHT_RECORDS),
        "flight_tail": flightrecorder.RECORDER.tail(flight_tail),
        "timeline": _timeline_section(),
    }


def collect(extra: Optional[Dict[str, Callable[[], dict]]] = None,
            flight_tail: int = 16) -> dict:
    """Gather every reachable snapshot — this process, every registered
    source (supervisor), and the caller's extra sources (the operator
    passes one that runs the solverd stats RPC) — then merge.  A source
    that throws becomes {"error": ...}: diagnostics must keep rendering
    exactly when part of the fleet is down."""
    try:
        snaps: Dict[str, dict] = {
            "operator": local_snapshot(flight_tail=flight_tail)}
    except Exception as e:  # noqa: BLE001 — the contract is absolute
        snaps = {"operator": {"error": str(e)[:200]}}
    with _lock:
        named = list(_sources.items())
    if extra:
        named += list(extra.items())
    for name, fn in named:
        try:
            snap = fn()
        except Exception as e:  # noqa: BLE001 — render what IS reachable
            snap = {"error": str(e)[:200]}
        if snap is not None:
            snaps[name] = snap
    return merge(snaps)


def merge(snapshots: Dict[str, dict]) -> dict:
    """Fold named per-process snapshots into one dashboard document:
    the raw per-process sections stay under `processes`, and the `fleet`
    rollup answers the operator's first-glance questions (is work
    flowing, is anything shedding/restarting/breaker-open, is the delta
    path engaged)."""
    def num(snap, *path, default=0.0):
        cur = snap
        for p in path:
            if not isinstance(cur, dict):
                return default
            cur = cur.get(p)
        return cur if isinstance(cur, (int, float)) else default

    fleet = {
        "queue_depth": sum(num(s, "queue_depth")
                           for s in snapshots.values()),
        "solves_total": sum(num(s, "solves_total")
                            for s in snapshots.values()),
        "shed": sum(max(num(s, "stats", "shed"), num(s, "shed"))
                    for s in snapshots.values()),
        "worker_restarts": max(
            (max(num(s, "service", "worker_restarts"),
                 num(s, "restarts")) for s in snapshots.values()),
            default=0.0),
        "breaker_state": max(
            (num(s, "service", "breaker_state")
             for s in snapshots.values()), default=0.0),
        "retries": sum(num(s, "service", "retries")
                       for s in snapshots.values()),
        "delta_passes": {},
        # the last-pass churn actually paid for, summed across
        # processes (ISSUE 20): with the index engaged this tracks the
        # dirty set, not the cluster — the first-glance O(churn) check
        "delta_groups_reencoded": sum(
            num(s, "delta", "groups_reencoded")
            for s in snapshots.values()),
        "spans_dropped": sum(num(s, "spans_dropped")
                             for s in snapshots.values()),
    }
    for s in snapshots.values():
        passes = s.get("delta", {}).get("passes") \
            if isinstance(s.get("delta"), dict) else None
        if isinstance(passes, dict):
            for k, v in passes.items():
                fleet["delta_passes"][k] = \
                    fleet["delta_passes"].get(k, 0) + v
    def items_of(sect, field):
        """A section field's dict items, or nothing — a partially
        written or foreign-schema snapshot (a worker one version
        behind) must degrade per FIELD, never raise into the
        dashboard's HTTP thread."""
        v = sect.get(field)
        return v.items() if isinstance(v, dict) else ()

    # placement rollup: per-reason unschedulable verdicts and the
    # per-constraint elimination attribution summed across processes
    # (the solverd worker's eliminations arrive via the stats RPC)
    placement = {"unschedulable": {}, "eliminations": {}}
    for s in snapshots.values():
        sect = s.get("placement")
        if not isinstance(sect, dict):
            continue
        for field in ("unschedulable", "eliminations"):
            for k, v in items_of(sect, field):
                if isinstance(v, (int, float)):
                    placement[field][k] = placement[field].get(k, 0) + v
    if placement["unschedulable"] or placement["eliminations"]:
        fleet["placement"] = placement
    # per-tenant rollup (the shared-fleet first-glance questions: who is
    # queued, who is being shed, what share of service each tenant got):
    # requests/sheds sum across processes; the fairness share normalizes
    # against the fleet total
    tenants: Dict[str, dict] = {}
    for s in snapshots.values():
        sect = s.get("tenants")
        if not isinstance(sect, dict):
            continue
        for t, v in items_of(sect, "requests"):
            if not isinstance(v, (int, float)):
                continue
            tenants.setdefault(t, {"requests": 0, "shed": 0,
                                   "queue_depth": 0})
            tenants[t]["requests"] += v
        for t, v in items_of(sect, "queue_depth"):
            if not isinstance(v, (int, float)):
                continue
            tenants.setdefault(t, {"requests": 0, "shed": 0,
                                   "queue_depth": 0})
            tenants[t]["queue_depth"] += v
        for key, v in items_of(sect, "shed"):
            if not isinstance(v, (int, float)):
                continue
            # label key is "tenant/reason" — reason never contains "/"
            t = key.rsplit("/", 1)[0]
            tenants.setdefault(t, {"requests": 0, "shed": 0,
                                   "queue_depth": 0})
            tenants[t]["shed"] += v
    total_req = sum(v["requests"] for v in tenants.values())
    for v in tenants.values():
        v["share"] = round(v["requests"] / total_req, 4) if total_req \
            else 0.0
    if tenants:
        fleet["tenants"] = tenants
    # cost & efficiency rollup (ISSUE 14): fleet $/hr and savings summed
    # across processes (only the controller-running operator carries
    # non-empty series, so the sum IS its view; a worker's empty section
    # adds nothing), audit verdicts summed, the lower-bound ratio the
    # max across reporters, packing efficiency the min (worst view).
    # Every read degrades per-field — a partial
    # or foreign-schema section must never break the dashboard.
    cost = {"hourly_total": 0.0, "hourly_by_pool": {}, "savings": {},
            "audit": {}, "packing_efficiency": {},
            "efficiency_lower_bound": None}
    cost_present = False
    for s in snapshots.values():
        sect = s.get("cost") if isinstance(s, dict) else None
        if not isinstance(sect, dict):
            continue
        cost_present = True
        for field, dest in (("fleet_hourly_cost", "hourly_by_pool"),
                            ("savings", "savings"),
                            ("audit", "audit")):
            src = sect.get(field)
            if not isinstance(src, dict):
                continue
            for k, v in src.items():
                if isinstance(v, (int, float)):
                    cost[dest][k] = cost[dest].get(k, 0) + v
        pe = sect.get("packing_efficiency")
        if isinstance(pe, dict):
            # ratios can't sum: take the MIN per resource — the
            # conservative (worst-packing) view, and deterministic when
            # two snapshots carry the same series (HA pair mid-failover,
            # stale worker), unlike last-writer-wins over dict order
            for k, v in pe.items():
                if isinstance(v, (int, float)):
                    cur = cost["packing_efficiency"].get(k)
                    cost["packing_efficiency"][k] = \
                        v if cur is None else min(cur, v)
        b = sect.get("efficiency_lower_bound")
        if isinstance(b, (int, float)) and b > 0:
            cur = cost["efficiency_lower_bound"]
            cost["efficiency_lower_bound"] = \
                b if cur is None else max(cur, b)
    if cost_present:
        cost["hourly_total"] = round(
            sum(cost["hourly_by_pool"].values()), 6)
        fleet["cost"] = cost
    return {"generated_at": time.time(),
            "processes": snapshots,
            "fleet": fleet}


# the ONE Content-Type every html-rendering debug route serves — the
# hand-rolled renderers used to each spell their own
HTML_CONTENT_TYPE = "text/html; charset=utf-8"


def _html_table(payload) -> str:
    """One escaped table: a dict renders as key/value rows; a list of
    flat dicts renders columnar (column set = union of keys in first-
    appearance order).  Non-scalar cells render as compact JSON —
    every cell value passes through html.escape (hostile reasons,
    zones, or pod names must never break the page)."""
    import html as _html
    import json as _json

    def cell(v) -> str:
        if isinstance(v, str):
            return _html.escape(v)
        return _html.escape(_json.dumps(v, default=str))

    if isinstance(payload, dict):
        rows = "".join(
            f"<tr><td>{cell(str(k))}</td><td>{cell(v)}</td></tr>"
            for k, v in sorted(payload.items(), key=lambda kv: str(kv[0])))
        return f"<table>{rows}</table>"
    cols: list = []
    for row in payload:
        for k in row:
            if k not in cols:
                cols.append(k)
    head = "".join(f"<th>{cell(str(c))}</th>" for c in cols)
    body = "".join(
        "<tr>" + "".join(f"<td>{cell(row.get(c, ''))}</td>"
                         for c in cols) + "</tr>"
        for row in payload)
    return f"<table><tr>{head}</tr>{body}</table>"


def html_page(title: str, sections) -> str:
    """The ONE debug-page renderer (`/debug/dashboard`, `/debug/explain`,
    `/debug/ledger` all render through here — they used to hand-roll
    three separate pages, drifting on charset and escaping).

    `sections` is an iterable of (heading, payload): a dict payload
    renders as a two-column table, a non-empty list of dicts as a
    columnar table, anything else as escaped pretty JSON in <pre>; a
    None heading omits the <h2>.  Serve the result with
    :data:`HTML_CONTENT_TYPE`."""
    import html as _html
    import json as _json
    parts = []
    for heading, payload in sections:
        if heading is not None:
            parts.append(f"<h2>{_html.escape(str(heading))}</h2>")
        if isinstance(payload, dict):
            parts.append(_html_table(payload))
        elif (isinstance(payload, list) and payload
              and all(isinstance(r, dict) for r in payload)):
            parts.append(_html_table(payload))
        else:
            body = _html.escape(
                _json.dumps(payload, indent=2, default=str))
            parts.append(f"<pre>{body}</pre>")
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{_html.escape(title)}</title>"
        "<style>body{font-family:monospace;margin:1.5em}"
        "table{border-collapse:collapse}"
        "td,th{border:1px solid #999;padding:2px 8px;text-align:left}"
        "pre{background:#f6f6f6;padding:8px;overflow-x:auto}</style>"
        f"</head><body><h1>{_html.escape(title)}</h1>"
        + "".join(parts) + "</body></html>")


def render_html(doc: dict) -> str:
    """One self-contained HTML page over the merged document — the
    no-tooling view (`GET /debug/dashboard?format=html`); the JSON form
    is the API."""
    fleet = {k: v for k, v in sorted(doc.get("fleet", {}).items())}
    sections = [("fleet", fleet)]
    sections += [(name, snap)
                 for name, snap in sorted(doc.get("processes", {}).items())]
    return html_page("karpenter-tpu operator dashboard", sections)


def reset() -> None:
    """Clear registered sources and the rate window (tests)."""
    with _lock:
        _sources.clear()
        _rate_state["ts"] = _rate_state["total"] = None
