"""Injected clock — every controller takes one, mirroring the reference's
`clock.Clock` injection (cmd/controller/main.go:47), so tests can step time
deterministically.
"""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> float:
        return time.time()


class FakeClock(Clock):
    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def step(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t
