"""TTL caches and the unavailable-offerings (ICE) cache.

The ICE cache is *the* feedback path from launch failures back into
scheduling (reference: pkg/cache/unavailableofferings.go:31-66 — key
`capacityType:instanceType:zone`, TTL 3 min per pkg/cache/cache.go:29, and a
seqnum that invalidates the instance-type provider's composite cache key on
every change).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Tuple

from karpenter_tpu.utils.clock import Clock, RealClock

# TTLs mirroring pkg/cache/cache.go:20-46
DEFAULT_TTL = 60.0
UNAVAILABLE_OFFERINGS_TTL = 180.0
INSTANCE_TYPES_ZONES_TTL = 300.0


class TTLCache:
    def __init__(self, ttl: float = DEFAULT_TTL, clock: Optional[Clock] = None,
                 on_evict=None):
        self.ttl = ttl
        self.clock = clock or RealClock()
        self.on_evict = on_evict  # called with (key, value) when an entry expires
        self._items: Dict[Any, Tuple[float, Any]] = {}

    def _expire(self, key: Any, value: Any) -> None:
        del self._items[key]
        if self.on_evict is not None:
            self.on_evict(key, value)

    def get(self, key: Any) -> Optional[Any]:
        item = self._items.get(key)
        if item is None:
            return None
        expires, value = item
        if self.clock.now() >= expires:
            self._expire(key, value)
            return None
        return value

    def sweep(self) -> int:
        """Evict every expired entry now (firing on_evict); returns count.
        Lazy expiry isn't enough for state whose *disappearance* must be
        observable — e.g. ICE entries aging out must bump the seqnum the
        instance-type cache key folds in (reference: OnEvicted callback in
        pkg/cache/unavailableofferings.go).
        """
        now = self.clock.now()
        expired = [(k, v) for k, (exp, v) in self._items.items() if now >= exp]
        for k, v in expired:
            self._expire(k, v)
        return len(expired)

    def set(self, key: Any, value: Any, ttl: Optional[float] = None) -> None:
        self._items[key] = (self.clock.now() + (ttl or self.ttl), value)

    def delete(self, key: Any) -> None:
        self._items.pop(key, None)

    def flush(self) -> None:
        self._items.clear()

    def keys(self) -> Iterator[Any]:
        now = self.clock.now()
        return iter([k for k, (exp, _) in self._items.items() if now < exp])

    def __len__(self) -> int:
        return sum(1 for _ in self.keys())

    def __contains__(self, key: Any) -> bool:
        return self.get(key) is not None


class UnavailableOfferings:
    """Insufficient-capacity backoff cache with a monotonically increasing
    sequence number; the instance-type provider folds the seqnum into its
    cache key so a capacity-error immediately invalidates cached catalogs
    (pkg/cache/unavailableofferings.go + instancetype.go:127-136).
    """

    def __init__(self, clock: Optional[Clock] = None,
                 ttl: float = UNAVAILABLE_OFFERINGS_TTL):
        self._cache = TTLCache(ttl=ttl, clock=clock,
                               on_evict=lambda k, v: self._bump())
        self._seq = 0

    def _bump(self) -> None:
        self._seq += 1

    @property
    def seqnum(self) -> int:
        # sweep first so TTL expirations are visible to cache-key readers
        self._cache.sweep()
        return self._seq

    @staticmethod
    def _key(capacity_type: str, instance_type: str, zone: str) -> str:
        return f"{capacity_type}:{instance_type}:{zone}"

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        return self._key(capacity_type, instance_type, zone) in self._cache

    def mark_unavailable(self, capacity_type: str, instance_type: str, zone: str,
                         reason: str = "InsufficientInstanceCapacity") -> None:
        self._cache.set(self._key(capacity_type, instance_type, zone), reason)
        self._bump()

    def delete(self, capacity_type: str, instance_type: str, zone: str) -> None:
        self._cache.delete(self._key(capacity_type, instance_type, zone))
        self._bump()

    def flush(self) -> None:
        self._cache.flush()
        self._bump()
