"""Minimal 5-field cron evaluation for disruption-budget windows.

Reference: NodePool.spec.disruption.budgets carry `schedule` (standard
cron, UTC) + `duration`; a budget is ACTIVE while now lies within
[latest schedule fire, fire + duration] (karpenter.sh_nodepools.yaml
budget fields; website/.../disruption.md budget scheduling). The
reference uses robfig/cron; this is the dependency-free equivalent for
the subset the CRD allows: numbers, `*`, lists, ranges, and `*/step`,
with the standard OR rule when both day-of-month and day-of-week are
restricted.
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import Optional, Set, Tuple

_BOUNDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
# name forms the reference's robfig ParseStandard accepts
_MONTHS = {n: i + 1 for i, n in enumerate(
    ("JAN", "FEB", "MAR", "APR", "MAY", "JUN",
     "JUL", "AUG", "SEP", "OCT", "NOV", "DEC"))}
_DOWS = {n: i for i, n in enumerate(
    ("SUN", "MON", "TUE", "WED", "THU", "FRI", "SAT"))}
_MACROS = {
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
}


class CronError(ValueError):
    pass


def _to_int(tok: str, names: dict) -> int:
    up = tok.upper()
    if up in names:
        return names[up]
    try:
        return int(tok)
    except ValueError:
        raise CronError(f"bad cron token {tok!r}") from None


def _parse_field(spec: str, lo: int, hi: int, names: dict) -> Optional[Set[int]]:
    """None = unrestricted (`*`)."""
    if spec == "*":
        return None
    out: Set[int] = set()
    for part in spec.split(","):
        step = None
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = _to_int(step_s, {})
            if step <= 0:
                raise CronError(f"bad step in {spec!r}")
        if part == "*":
            lo_p, hi_p = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo_p, hi_p = _to_int(a, names), _to_int(b, names)
        else:
            lo_p = _to_int(part, names)
            # robfig semantics: 'N/step' means N through max, stepped;
            # a bare 'N' is the single value
            hi_p = hi if step is not None else lo_p
        if lo_p < lo or hi_p > hi or lo_p > hi_p:
            raise CronError(f"{spec!r} out of range [{lo},{hi}]")
        out.update(range(lo_p, hi_p + 1, step or 1))
    return out


def parse(schedule: str) -> Tuple[Optional[Set[int]], ...]:
    schedule = _MACROS.get(schedule.strip().lower(), schedule)
    fields = schedule.split()
    if len(fields) != 5:
        raise CronError(f"want 5 cron fields, got {len(fields)}: {schedule!r}")
    field_names = ({}, {}, {}, _MONTHS, _DOWS)
    return tuple(_parse_field(f, lo, hi, names)
                 for f, (lo, hi), names in zip(fields, _BOUNDS, field_names))


def _date_matches(parsed, d) -> bool:
    _, _, dom, month, dow = parsed
    if month is not None and d.month not in month:
        return False
    # standard cron OR rule: when BOTH dom and dow are restricted, either
    # matching suffices; otherwise the restricted one must match
    cron_dow = (d.weekday() + 1) % 7  # cron: 0 = Sunday
    dom_ok = dom is None or d.day in dom
    dow_ok = dow is None or cron_dow in dow
    if dom is not None and dow is not None:
        return dom_ok or dow_ok
    return dom_ok and dow_ok


def _best_time(minute, hour, before=None):
    """Latest (h, m) from the parsed sets, optionally at/before `before`;
    None when the day has no matching time early enough."""
    hours = sorted(hour, reverse=True) if hour is not None \
        else list(range(23, -1, -1))
    minutes = sorted(minute, reverse=True) if minute is not None \
        else list(range(59, -1, -1))
    if before is None:
        return hours[0], minutes[0]
    bh, bm = before
    for h in hours:
        if h > bh:
            continue
        if h < bh:
            return h, minutes[0]
        for m in minutes:
            if m <= bm:
                return h, m
    return None


_MAX_LOOKBACK_DAYS = 400  # covers yearly schedules (the sparsest the
# 5-field grammar can express: one date per year)
_last_fire_cache: dict = {}


def last_fire(schedule: str, now_ts: float,
              lookback_days: int = _MAX_LOOKBACK_DAYS) -> Optional[float]:
    """Epoch seconds of the most recent fire at/before now (UTC), or None
    if none within the lookback. Steps by DAY (date-field match first,
    then the latest in-day time arithmetically) instead of scanning
    minute-by-minute — a monthly schedule costs ~35 date checks, not
    ~50k datetime decrements. Cached per (schedule, lookback, minute).

    `lookback_days` exists for callers whose window extends further than
    a year past the fire (a duration like '9000h' is legal in the
    reference CRD): the in-window check must see a fire as old as its
    duration, or an open freeze silently reads as closed — the unsafe
    direction. The reference's robfig-based check has no horizon at all;
    ours is day-stepped, so a wide horizon costs one date check per day.
    """
    minute_bucket = int(now_ts // 60)
    key = (schedule, lookback_days, minute_bucket)
    if key in _last_fire_cache:
        return _last_fire_cache[key]
    parsed = parse(schedule)
    now_dt = datetime.fromtimestamp(now_ts, tz=timezone.utc)
    out: Optional[float] = None
    for day_off in range(lookback_days):
        d = (now_dt - timedelta(days=day_off)).date()
        if not _date_matches(parsed, d):
            continue
        before = (now_dt.hour, now_dt.minute) if day_off == 0 else None
        hm = _best_time(parsed[0], parsed[1], before)
        if hm is None:
            continue  # same-day fire hasn't happened yet; keep looking back
        out = datetime(d.year, d.month, d.day, hm[0], hm[1],
                       tzinfo=timezone.utc).timestamp()
        break
    if len(_last_fire_cache) > 4096:
        _last_fire_cache.clear()
    _last_fire_cache[key] = out
    return out


def in_window(schedule: Optional[str], duration: Optional[float],
              now_ts: float) -> bool:
    """Whether a budget's schedule window is open at now. No schedule =
    always open. Schedule WITHOUT duration is a config error the CRD
    would reject — fail safe by treating the window as always open (the
    budget binds) rather than silently dropping a freeze the user
    configured. Raises CronError on an unparseable schedule (callers
    fail safe the same way)."""
    if schedule is None:
        return True
    if duration is None:
        return True
    # the lookback must reach at least `duration` into the past: a fire
    # older than the default horizon can still hold the window open when
    # its duration spans months (ADVICE r3: yearly schedule + multi-month
    # duration read as closed — the direction that silently drops a
    # configured disruption freeze)
    lookback = max(_MAX_LOOKBACK_DAYS, int(float(duration) // 86400) + 2)
    fire = last_fire(schedule, now_ts, lookback_days=lookback)
    if fire is None:
        return False
    return fire <= now_ts < fire + float(duration)
