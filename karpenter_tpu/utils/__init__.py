"""Shared runtime utilities: clocks, TTL caches, the ICE feedback cache."""

from karpenter_tpu.utils.clock import Clock, FakeClock, RealClock
from karpenter_tpu.utils.cache import TTLCache, UnavailableOfferings

__all__ = ["Clock", "FakeClock", "RealClock", "TTLCache", "UnavailableOfferings"]
