"""Shared runtime utilities: clocks, TTL caches, the ICE feedback cache,
and the request-coalescing batcher."""

from karpenter_tpu.utils.clock import Clock, FakeClock, RealClock
from karpenter_tpu.utils.cache import TTLCache, UnavailableOfferings
from karpenter_tpu.utils.batcher import Batcher

__all__ = ["Batcher", "Clock", "FakeClock", "RealClock", "TTLCache",
           "UnavailableOfferings"]
