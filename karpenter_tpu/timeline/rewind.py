"""Rewind engine: replay a timeline against a live control plane
(ISSUE 17 tentpole part 2).

Takes an ordered event stream — recorded (`timeline/recorder.py` spill)
or synthetic (`timeline/generators.py`) — and re-runs it against a real
Environment, either stepped deterministically by the engine ("manager"
driver: fake-clock set + `env.settle()` per tick — the driver seek
bit-identity is defined on) or through a real Operator's watch-driven
run loop ("operator" driver: the macro-bench and smoke-gate mode the
ISSUE's 'against a real Operator' acceptance pins).  The trajectory
invariant auditors (`timeline/invariants.py`) ride along: gang
atomicity and priority inversions on every solve via the SolveProbe,
the shadow audit sampler forced to rate=1, and ledger-hex-exactness +
lost-pod reconciliation at the end.

Checkpoint/seek: the stream is batched into ticks (events sharing one
`at`); after every tick both drivers are at a well-defined state, so a
checkpoint at event count k (snapped to its tick boundary) digests
identically whether reached by straight-line replay or by `seek` —
replay events [0..k) on a fresh environment, digest, compare
bit-for-bit.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from karpenter_tpu.timeline import events as ev
from karpenter_tpu.timeline import invariants as inv

_BASE_CLOCK = 1_000_000.0  # FakeClock's own default start


def normalize(events: List[dict]) -> List[dict]:
    """Sort a stream by replay offset.  Synthetic events carry `at`
    already; recorded spills carry wall `ts` — rebased so the first
    event is at 0.  Store observations other than pod add/delete are
    dropped (they are the controllers' own output; replaying them
    would double-apply), with pods promoted to drive events."""
    out = []
    ts0 = None
    for e in events:
        if not isinstance(e, dict) or "kind" not in e:
            continue
        kind = e["kind"]
        at = e.get("at")
        if at is None:
            ts = e.get("ts")
            if ts is None:
                continue
            if ts0 is None:
                ts0 = float(ts)
            at = float(ts) - ts0
        if ev.is_store(kind):
            if kind == ev.store_event("pods", "added"):
                kind = ev.POD_ADD
            elif kind == ev.store_event("pods", "deleted"):
                kind = ev.POD_REMOVE
            else:
                continue
        out.append({"at": float(at), "kind": kind,
                    "name": e.get("name", ""), "data": e.get("data")})
    out.sort(key=lambda x: (x["at"], x["kind"], x["name"]))
    return out


def ticks_of(events: List[dict]) -> List[List[dict]]:
    """Group consecutive events sharing one `at` into ticks — the
    settle/checkpoint granularity."""
    ticks: List[List[dict]] = []
    for e in events:
        if ticks and ticks[-1][0]["at"] == e["at"]:
            ticks[-1].append(e)
        else:
            ticks.append([e])
    return ticks


def make_pod(name: str, data: Optional[dict]):
    """Invert `recorder.pod_spec` (dense `requests` vector) or a
    generator's readable request map (`cpu`/`memory` strings) into a
    Pod ready for `cluster.pods.create`."""
    from karpenter_tpu.models import ObjectMeta, Pod
    from karpenter_tpu.models.resources import Resources
    data = data or {}
    if data.get("requests"):
        req = Resources(v=[float(x) for x in data["requests"]])
    else:
        req = Resources.parse({"cpu": data.get("cpu", "250m"),
                               "memory": data.get("memory", "512Mi")})
    return Pod(meta=ObjectMeta(name=name,
                               labels=dict(data.get("labels") or {}),
                               annotations=dict(
                                   data.get("annotations") or {})),
               requests=req)


class RewindEngine:
    """One replay run: fresh Environment, probed solver, armed shadow
    audit, timeline re-recording ON (a replay leaves its own recorded
    timeline — the recorder is part of what's being exercised)."""

    def __init__(self, events: List[dict], *,
                 options=None, catalog_spec=None, audit: bool = True,
                 settle_rounds: int = 80,
                 resolution: Optional[float] = None):
        self.events = normalize(events)
        if resolution:
            # replay frame rate: quantize offsets down to `resolution`
            # seconds so a dense stream (every arrival at its own
            # millisecond) batches into a bounded number of ticks —
            # each tick is one settle/quiesce, and THAT is the wall
            # cost of replay, not the event count.  Deterministic, and
            # identical for straight-line and seek runs (both quantize
            # before ticks are formed), so bit-identity is preserved.
            self.events = [
                dict(e, at=(e["at"] // resolution) * resolution)
                for e in self.events]
            self.events.sort(key=lambda x: (x["at"], x["kind"],
                                            x["name"]))
        self.ticks = ticks_of(self.events)
        self.audit = audit
        self.settle_rounds = settle_rounds
        self._catalog_spec = catalog_spec
        self._options = options
        self.auditor = inv.TrajectoryAuditor()
        # serializes the engine's event-apply loop against the probed
        # solver's solve+audit window (see SolveProbe.solve): a tick is
        # applied atomically with respect to any in-flight solve
        self._world_lock = threading.RLock()
        self.env = None
        self.skipped: Dict[str, int] = {}

    # -- environment -------------------------------------------------------
    def _build_env(self):
        from karpenter_tpu.env import Environment
        from karpenter_tpu.models import NodePool, ObjectMeta
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.utils.clock import FakeClock
        opts = self._options or Options(batch_idle_duration=0)
        env = Environment(clock=FakeClock(), options=opts,
                          catalog_spec=self._catalog_spec)
        env.add_default_nodeclass()
        env.cluster.nodepools.create(
            NodePool(meta=ObjectMeta(name="default")))
        probe = inv.SolveProbe(env.solver, self.auditor,
                               world_lock=self._world_lock)
        # all three references point at ONE shared GatedSolver — the
        # probe must replace every alias or a path escapes the judges
        env.solver = probe
        env.provisioner.solver = probe
        env.disruption.solver = probe
        self.env = env
        return env

    # -- event application -------------------------------------------------
    def _skip(self, why: str) -> None:
        self.skipped[why] = self.skipped.get(why, 0) + 1

    def _live_spot_ids(self) -> List[str]:
        cloud = self.env.cloud
        return sorted(
            iid for iid, inst in cloud.instances.items()
            if inst.capacity_type == "spot" and not inst.interrupted
            and inst.state == "running")

    def apply(self, e: dict) -> None:
        """Apply ONE drive event to the live environment."""
        kind, name, data = e["kind"], e["name"], e.get("data") or {}
        cluster = self.env.cluster
        if kind == ev.POD_ADD:
            if cluster.pods.get(name) is not None:
                self._skip("pod_add_duplicate")
                return
            cluster.pods.create(make_pod(name, data))
            self.auditor.expected_pods.add(name)
        elif kind == ev.POD_REMOVE:
            self.auditor.expected_pods.discard(name)
            if cluster.pods.get(name) is None:
                self._skip("pod_remove_unknown")
                return
            cluster.pods.delete(name)
        elif kind == ev.SPOT_RECLAIM:
            spot = self._live_spot_ids()
            if not spot:
                self._skip("spot_reclaim_no_capacity")
                return
            pick = data.get("pick")
            iid = data.get("instance_id")
            if iid not in spot:
                iid = spot[int(pick or 0) % len(spot)]
            self.env.cloud.interrupt_spot(iid)
        elif kind == ev.PRICE_REFRESH:
            self.env.pricing.update()
        elif kind in (ev.FAULT_INJECT, ev.WORKER_CRASH):
            from karpenter_tpu.utils import faults
            faults.arm(data.get("point", "solver.dispatch"),
                       data.get("mode", "error"),
                       arg=data.get("arg"),
                       times=data.get("times", 1),
                       after=int(data.get("after", 0) or 0))
        elif kind == ev.WORKER_RESTART:
            from karpenter_tpu.utils import faults
            faults.disarm()
        elif kind in (ev.GANG_ARRIVAL, ev.PRIORITY_ARRIVAL,
                      ev.CHECKPOINT):
            pass  # scenario markers — bookkeeping, not inputs
        else:
            self._skip(f"unknown_kind:{kind}")

    # -- drivers -----------------------------------------------------------
    def _drive_manager(self, checkpoint_at, stop_after):
        """Deterministic single-thread driver: per tick, set the fake
        clock to the tick's offset, apply its events, settle to a fixed
        point.  The driver seek bit-identity is defined on."""
        clock = self.env.clock
        checkpoints: Dict[int, str] = {}
        applied = 0
        for tick in self.ticks:
            if stop_after is not None and applied >= stop_after:
                break
            clock.set(_BASE_CLOCK + tick[0]["at"])
            for e in tick:
                self.apply(e)
                applied += 1
            self.env.settle(self.settle_rounds)
            for k in checkpoint_at:
                if k not in checkpoints and applied >= k:
                    checkpoints[k] = inv.state_digest(
                        self.env.cluster, self.env.pricing)
        self.env.settle(self.settle_rounds)
        return applied, checkpoints

    def _drive_operator(self, checkpoint_at, stop_after, speedup,
                        operator_kw=None):
        """Replay through a REAL Operator: its watch-driven run loop
        reconciles in its own thread while the engine feeds events and
        steps the fake clock.  `speedup` paces wall time (None = as
        fast as the operator drains); convergence per tick is
        generation-stability, not sleep-polling."""
        from karpenter_tpu.operator.operator import Operator
        op = Operator(options=self.env.options, env=self.env,
                      metrics_port=0, health_port=0,
                      reconcile_interval=0.02, **(operator_kw or {}))
        t = threading.Thread(target=op.run, daemon=True,
                             name="kt-rewind-operator")
        t.start()
        checkpoints: Dict[int, str] = {}
        applied = 0
        clock = self.env.clock
        try:
            prev_at = self.ticks[0][0]["at"] if self.ticks else 0.0
            for tick in self.ticks:
                if stop_after is not None and applied >= stop_after:
                    break
                if speedup:
                    gap = (tick[0]["at"] - prev_at) / float(speedup)
                    if gap > 0:
                        time.sleep(min(gap, 5.0))
                prev_at = tick[0]["at"]
                # a tick applies atomically w.r.t. the solver: the
                # operator's watch wakes it on the tick's FIRST event,
                # and a solve that encodes mid-tick (then audits
                # against post-tick state) is the one remaining way a
                # phantom divergence can race in
                with self._world_lock:
                    clock.set(_BASE_CLOCK + tick[0]["at"])
                    for e in tick:
                        self.apply(e)
                        applied += 1
                self._quiesce()
                for k in checkpoint_at:
                    if k not in checkpoints and applied >= k:
                        checkpoints[k] = inv.state_digest(
                            self.env.cluster, self.env.pricing)
            self._quiesce(timeout=10.0)
        finally:
            op.stop()
            t.join(timeout=10.0)
        return applied, checkpoints

    def _quiesce(self, timeout: float = 10.0) -> None:
        """Wait for the operator thread to drain the tick: done when no
        pod is left pending AND the cluster generation has held still
        across consecutive observation windows.  Generation stability
        alone is not enough — a first solve (compile included) can hold
        the generation flat for seconds while work is very much in
        flight — so pending pods keep the wait alive until the deadline
        (a crashed-solver window legitimately times out with pods
        pending; the next tick's retry seats them)."""
        deadline = time.monotonic() + timeout
        stable = 0
        cluster = self.env.cluster
        gen = cluster.generation
        while time.monotonic() < deadline:
            time.sleep(0.05)
            pending = any(not p.scheduled and not p.meta.deleting
                          for p in cluster.pods.list())
            g = cluster.generation
            if g == gen and not pending:
                stable += 1
                if stable >= 3:
                    return
            else:
                stable = 0
                gen = g

    # -- entry points ------------------------------------------------------
    def run(self, driver: str = "manager", speedup: Optional[float] = None,
            checkpoint_at=(), stop_after: Optional[int] = None) -> dict:
        from karpenter_tpu.solver import audit as audit_mod
        from karpenter_tpu.utils import faults, ledger
        # arm rate=1 shadow audit through the knob's owner module (it
        # returns the restore callable honoring the prior spelling)
        restore_audit = audit_mod.arm("1") if self.audit else None
        audit_before = inv.audit_series()
        # the hex-exact judge must see EVERY row of this replay — widen
        # the ring past the default 512 unless the caller pinned it
        ledger.ensure_buffer(65536)
        ledger_seq_before = ledger.LEDGER.last_seq() or 0
        self._build_env()
        t0 = time.perf_counter()
        try:
            if driver == "operator":
                applied, checkpoints = self._drive_operator(
                    tuple(checkpoint_at), stop_after, speedup)
            else:
                applied, checkpoints = self._drive_manager(
                    tuple(checkpoint_at), stop_after)
        finally:
            faults.disarm()
            if restore_audit is not None:
                audit_mod.SAMPLER.drain(timeout=60.0)
                restore_audit()
        wall = time.perf_counter() - t0
        audit_after = inv.audit_series()
        # judge only THIS replay's ledger rows (the ring may carry a
        # prior run's history in one process)
        records = [r for r in ledger.LEDGER.tail(1 << 20)
                   if r.get("seq", 0) > ledger_seq_before]
        report = self.auditor.report(
            self.env.cluster, records,
            inv.audit_deltas(audit_before, audit_after))
        cluster = self.env.cluster
        report.update({
            "driver": driver,
            "events_total": len(self.events),
            "events_applied": applied,
            "events_skipped": dict(self.skipped),
            "wall_s": round(wall, 3),
            "events_per_s": round(applied / wall, 1) if wall > 0 else None,
            "pods_final": len(cluster.pods.list()),
            "scheduled_final": sum(
                1 for p in cluster.pods.list() if p.scheduled),
            "nodes_final": len(cluster.nodes.list(
                lambda n: not n.meta.deleting)),
            "digest": inv.state_digest(cluster, self.env.pricing),
            "checkpoints": checkpoints,
        })
        report["invariants_held"] = all((
            report["ledger_hex_exact"],
            report["zero_gang_atomicity_violations"],
            report["zero_priority_inversions"],
            report["audit_clean"],
            report["zero_lost_pods"]))
        return report


def replay(events: List[dict], **kw) -> dict:
    """One-shot convenience: build an engine, run, return the report."""
    driver = kw.pop("driver", "manager")
    speedup = kw.pop("speedup", None)
    checkpoint_at = kw.pop("checkpoint_at", ())
    stop_after = kw.pop("stop_after", None)
    return RewindEngine(events, **kw).run(
        driver=driver, speedup=speedup, checkpoint_at=checkpoint_at,
        stop_after=stop_after)


def seek(events: List[dict], k: int, **kw) -> dict:
    """Reconstruct the cluster at event k (snapped to its tick
    boundary): replay [0..k) on a fresh deterministic environment and
    digest.  `seek_check` compares this against the straight-line run's
    checkpoint at the same k — the bit-identity contract."""
    eng = RewindEngine(events, **kw)
    k = snap_to_tick(eng.ticks, k)
    report = eng.run(driver="manager", stop_after=k)
    return {"k": k, "digest": report["digest"], "report": report}


def snap_to_tick(ticks: List[List[dict]], k: int) -> int:
    """Checkpoint granularity is the tick: round k up to the end of the
    tick containing event index k-1 (state mid-tick is not defined —
    the engine settles per tick, not per event)."""
    total = 0
    for tick in ticks:
        total += len(tick)
        if total >= k:
            return total
    return total


def seek_check(events: List[dict], k: int, **kw) -> dict:
    """The acceptance check: straight-line replay with a checkpoint at
    k vs an independent seek to k — digests must match bit-for-bit."""
    eng = RewindEngine(events, **kw)
    k = snap_to_tick(eng.ticks, k)
    straight = eng.run(driver="manager", checkpoint_at=(k,))
    sought = seek(events, k, **kw)
    a = straight["checkpoints"].get(k)
    b = sought["digest"]
    return {"k": k, "straight_digest": a, "seek_digest": b,
            "bit_identical": bool(a) and a == b,
            "straight": straight, "seek": sought["report"]}
