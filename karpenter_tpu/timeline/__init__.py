"""Cluster timeline: recorder + rewind engine (ISSUE 17).

The package is split so the recorder half stays import-light (it is on
the `Cluster.mutated` hot path): this __init__ exposes only the event
registry and the recorder.  The heavyweight halves — `rewind` (builds
an Environment / Operator), `generators`, and `invariants` — are
imported explicitly by their consumers (tools/kt_rewind.py,
benchmarks/config11_rewind.py, hack/rewind_smoke.py).
"""

from karpenter_tpu.timeline import events  # noqa: F401
from karpenter_tpu.timeline.recorder import (  # noqa: F401
    RECORDER,
    emit,
    load_events,
    pod_spec,
    record_store_mutation,
    recording_enabled,
)
