"""Synthetic timeline generators (ISSUE 17 tentpole part 3).

Each generator returns a list of plain event dicts —
``{"at": <seconds from stream start>, "kind": <events.*>, "name": ...,
"data": {...}}`` — sorted by ``at`` and fully determined by its
``seed``: the scenario classes the related work motivates (KubePACS
spot-interruption storms, "Priority Matters" priority waves, diurnal
load from public cluster traces) as seeded, composable building
blocks.  `compose` merges streams into one ordered timeline;
`rewind.RewindEngine` applies it against a live Environment/Operator.

Pod-carrying events put a human-readable request map in ``data``
(``{"cpu": "500m", "memory": "1Gi"}``) rather than the dense vector the
recorder captures — both shapes replay through `rewind.make_pod`.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from karpenter_tpu.models import wellknown
from karpenter_tpu.timeline import events as ev


def _pod(at: float, name: str, cpu: str, mem: str,
         annotations: Optional[Dict[str, str]] = None,
         labels: Optional[Dict[str, str]] = None) -> dict:
    return {"at": round(at, 3), "kind": ev.POD_ADD, "name": name,
            "data": {"cpu": cpu, "memory": mem,
                     "annotations": annotations or {},
                     "labels": labels or {}}}


def _remove(at: float, name: str) -> dict:
    return {"at": round(at, 3), "kind": ev.POD_REMOVE, "name": name,
            "data": None}


def diurnal_load(seed: int = 0, duration: float = 21600.0,
                 step: float = 120.0, base: int = 1, peak: int = 8,
                 lifetime: float = 1800.0, cpu: str = "500m",
                 mem: str = "1Gi", prefix: str = "diurnal") -> List[dict]:
    """A compressed day: per-step arrivals follow one sinusoidal cycle
    from ``base`` to ``peak`` pods, each living ``lifetime`` seconds
    (±25%, seeded) before its pod.remove.  The background hum every
    other scenario rides on top of."""
    rng = random.Random(seed)
    out: List[dict] = []
    i = 0
    t = 0.0
    while t < duration:
        phase = math.sin(math.pi * (t / duration))  # 0 → 1 → 0
        arrivals = base + int(round((peak - base) * phase))
        for _ in range(arrivals):
            name = f"{prefix}-{i}"
            i += 1
            at = t + rng.uniform(0.0, step)
            out.append(_pod(at, name, cpu, mem))
            life = lifetime * rng.uniform(0.75, 1.25)
            if at + life < duration:
                out.append(_remove(at + life, name))
        t += step
    out.sort(key=lambda e: (e["at"], e["kind"], e["name"]))
    return out


def spot_storm(at: float, reclaims: int = 12, spacing: float = 5.0,
               seed: int = 0) -> List[dict]:
    """A KubePACS-style interruption storm: ``reclaims`` spot
    terminations starting at ``at``, one every ``spacing`` seconds
    (±50%, seeded).  Each event carries a deterministic ``pick`` index;
    replay resolves it against the sorted list of live spot instances
    at fire time, so the storm is reproducible without knowing instance
    ids in advance (an unresolvable pick — no spot capacity up — is
    counted, not failed)."""
    rng = random.Random(seed)
    out = []
    t = at
    for i in range(reclaims):
        out.append({"at": round(t, 3), "kind": ev.SPOT_RECLAIM,
                    "name": f"storm-{i}", "data": {"pick": i}})
        t += spacing * rng.uniform(0.5, 1.5)
    return out


def gang_burst(at: float, gangs: int = 4, size: int = 4,
               topology: str = "", cpu: str = "500m", mem: str = "1Gi",
               spacing: float = 2.0, prefix: str = "gang",
               seed: int = 0) -> List[dict]:
    """``gangs`` all-or-nothing gangs of ``size`` members arriving in a
    burst — the tightly-coupled multi-node arrivals PR 14's atomicity
    audit exists for.  ``topology`` (e.g. a zone label key's domain
    semantics) pins adjacency when non-empty."""
    rng = random.Random(seed)
    out = []
    t = at
    for g in range(gangs):
        gname = f"{prefix}-{g}"
        ann = {wellknown.GANG_NAME_ANNOTATION: gname,
               wellknown.GANG_SIZE_ANNOTATION: str(size)}
        if topology:
            ann[wellknown.GANG_TOPOLOGY_ANNOTATION] = topology
        for m in range(size):
            out.append(_pod(t + rng.uniform(0.0, 0.5),
                            f"{gname}-m{m}", cpu, mem, annotations=ann))
        t += spacing
    out.sort(key=lambda e: (e["at"], e["kind"], e["name"]))
    return out


def priority_wave(at: float, bands=((1000, 4), (0, 8), (-10, 8)),
                  cpu: str = "500m", mem: str = "1Gi",
                  spacing: float = 1.0, prefix: str = "prio",
                  seed: int = 0) -> List[dict]:
    """A 'Priority Matters' wave: for each ``(priority, count)`` band,
    ``count`` pods carrying the priority annotation arrive together —
    high bands must never be stranded behind low ones
    (priority_inversion_audit is the replay judge)."""
    rng = random.Random(seed)
    out = []
    t = at
    for prio, count in bands:
        ann = {wellknown.PRIORITY_ANNOTATION: str(prio)}
        for i in range(count):
            out.append(_pod(t + rng.uniform(0.0, 0.5),
                            f"{prefix}-p{prio}-{i}", cpu, mem,
                            annotations=ann))
        t += spacing
    out.sort(key=lambda e: (e["at"], e["kind"], e["name"]))
    return out


def crash_schedule(crash_at: float, restart_after: float = 60.0,
                   worker: str = "solver") -> List[dict]:
    """One worker crash/restart pair: replayed as a one-shot
    `solver.dispatch` error fault (the PR 7 matrix point on the
    in-process solve path) armed at ``crash_at`` and disarmed
    ``restart_after`` seconds later.  The GatedSolver's degrade path
    must absorb it — pods stay pending and retry, never vanish."""
    return [
        {"at": round(crash_at, 3), "kind": ev.WORKER_CRASH,
         "name": worker, "data": {"point": "solver.dispatch",
                                  "mode": "error", "times": 1}},
        {"at": round(crash_at + restart_after, 3),
         "kind": ev.WORKER_RESTART, "name": worker, "data": None},
    ]


def compose(*streams: List[dict]) -> List[dict]:
    """Merge streams into one timeline ordered by (at, kind, name) —
    a total, input-order-independent sort so composed scenarios replay
    identically however they were assembled."""
    out = [dict(e) for s in streams for e in s]
    out.sort(key=lambda e: (e.get("at", 0.0), e.get("kind", ""),
                            e.get("name", "")))
    return out


def import_trace(path: str, time_key: str = "ts", name_key: str = "name",
                 cpu_key: str = "cpu", mem_key: str = "memory",
                 end_key: str = "end") -> List[dict]:
    """Importer skeleton for public cluster traces (Google/Alibaba-style
    task-event tables flattened to JSONL): each line with a timestamp
    and a name becomes a pod.add (requests from ``cpu_key``/``mem_key``,
    defaulting small), an ``end_key`` adds the matching pod.remove.
    Rows that don't parse are skipped and counted in the returned
    list's sidecar (``import_trace.skipped`` after the call) — the
    hook real trace adapters grow from, not a finished converter."""
    import json
    out: List[dict] = []
    skipped = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
                at = float(row[time_key])
                name = str(row[name_key])
            except (ValueError, KeyError, TypeError):
                skipped += 1
                continue
            out.append(_pod(at, name, str(row.get(cpu_key, "250m")),
                            str(row.get(mem_key, "512Mi"))))
            end = row.get(end_key)
            if end is not None:
                try:
                    out.append(_remove(float(end), name))
                except (TypeError, ValueError):
                    skipped += 1
    import_trace.skipped = skipped
    out.sort(key=lambda e: (e["at"], e["kind"], e["name"]))
    return out


import_trace.skipped = 0
