"""Timeline event-kind registry (ISSUE 17 tentpole part 1).

This module is the ONE place a timeline event kind may be spelled as a
string literal — the same single-owner discipline the reason-code
registry (solver/explain.py) enforces for event reasons, and it is
gated the same way: the kt-lint observability-conformance rule flags
any `emit("literal", ...)` call outside this module.  Everything else
imports the constants (or builds store kinds through `store_event`),
so renaming a kind is a one-file change and `KINDS` is always the
complete catalogue the docs table and `/debug/timeline?kind=` filter
can trust.

Two families:

  * **drive kinds** — replayable inputs.  A recorded or synthetic
    stream of these, applied in order by `timeline/rewind.py`, is
    sufficient to reproduce a cluster trajectory: pod arrivals and
    departures, spot reclaims, price refreshes, fault injections,
    worker crash/restart schedule points, and the gang/priority
    arrival markers the generators stamp for scenario bookkeeping.
  * **store kinds** — observations.  The recorder hook inside
    `Cluster.mutated` captures every informer-cache mutation as
    `store.<kind>.<op>` (e.g. `store.nodeclaims.added`); they document
    what the controllers DID and are skipped by the rewind engine
    (replaying them would double-apply the controllers' own work),
    with one exception: `store.pods.added/deleted` carry enough spec
    to be promoted to `pod.add`/`pod.remove` when replaying a recorded
    (rather than synthetic) stream.
"""

from __future__ import annotations

from typing import Dict

# --- drive kinds (replayable) -------------------------------------------
POD_ADD = "pod.add"
POD_REMOVE = "pod.remove"
SPOT_RECLAIM = "spot.reclaim"
PRICE_REFRESH = "price.refresh"
FAULT_INJECT = "fault.inject"
WORKER_CRASH = "worker.crash"
WORKER_RESTART = "worker.restart"
GANG_ARRIVAL = "gang.arrival"
PRIORITY_ARRIVAL = "priority.arrival"
CHECKPOINT = "checkpoint"

DRIVE_KINDS: Dict[str, str] = {
    POD_ADD: "a pending pod entered the cluster (data carries the "
             "dense request vector + annotations for replay)",
    POD_REMOVE: "a pod left the cluster (completion or deletion)",
    SPOT_RECLAIM: "the cloud reclaimed a spot instance "
                  "(KubePACS-style interruption)",
    PRICE_REFRESH: "the pricing catalog was refreshed",
    FAULT_INJECT: "a fault-matrix point was armed "
                  "(utils/faults.py, PR 7 matrix)",
    WORKER_CRASH: "schedule point: crash the solve worker "
                  "(replayed as a one-shot solver.dispatch fault)",
    WORKER_RESTART: "schedule point: the crashed worker came back "
                    "(replayed as faults.disarm)",
    GANG_ARRIVAL: "first member of a gang arrived (marker; the "
                  "members themselves are pod.add events)",
    PRIORITY_ARRIVAL: "first pod of a priority band arrived (marker)",
    CHECKPOINT: "state-digest checkpoint marker (seek anchor)",
}

# --- store kinds (observations) -----------------------------------------
STORE_PREFIX = "store."
STORE_KINDS = ("pods", "nodes", "nodeclaims", "nodepools", "nodeclasses")
STORE_OPS = ("added", "modified", "deleting", "deleted")


def store_event(kind: str, op: str) -> str:
    """`store.<kind>.<op>` — the observation kind for one informer-cache
    mutation.  The only sanctioned way to build one outside this module."""
    return STORE_PREFIX + kind + "." + op


KINDS: Dict[str, str] = dict(DRIVE_KINDS)
for _k in STORE_KINDS:
    for _op in STORE_OPS:
        KINDS[store_event(_k, _op)] = (
            f"informer-cache mutation: {_k} {_op} (observation)")


def is_drive(kind: str) -> bool:
    """True for kinds the rewind engine applies as inputs."""
    return kind in DRIVE_KINDS


def is_store(kind: str) -> bool:
    """True for recorded informer-cache observations."""
    return isinstance(kind, str) and kind.startswith(STORE_PREFIX)


def describe(kind: str) -> str:
    return KINDS.get(kind, "(unregistered kind)")
