"""Trajectory invariant auditors for timeline replay (ISSUE 17 part 4).

A replayed timeline is only a macro-bench if something can FAIL it.
This module holds the judges the rewind engine runs continuously:

  * **gang atomicity** — every solve's result through the shared
    `gang_placement_audit` (the ONE implementation the gang tests and
    the config9 bench already trust): no partial placement, no
    cross-domain adjacency split, ever, across the whole trajectory.
  * **priority inversions** — every solve through
    `priority_inversion_audit` with the result's attached preemption
    plans: a stranded high-priority pod whose seat one eviction could
    free is a trajectory failure, not a log line.
  * **ledger-hex-exact cost trajectory** — every ledger row's
    `fleet_cost_after` must equal `before + cost_delta` bit-for-bit
    (IEEE hex compare, not an epsilon) and `cost_delta_hex` must match
    its float re-encoded: the fleet $/hr chain never breaks.
  * **audit-clean solves** — with the shadow sampler at rate=1, the
    diverged/error verdict counters must not move during replay.
  * **zero lost pods** — set reconciliation between what the timeline
    fed in (adds minus removes) and what the cluster holds at the end:
    a silently-dropped pod is the one failure mode no per-solve check
    can see.

The solve-level judges attach via `SolveProbe`, a transparent wrapper
around the shared GatedSolver (env.solver / provisioner.solver /
disruption.solver all point at the same instance, so the engine
re-points all three).
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Dict, List, Optional


class SolveProbe:
    """Transparent GatedSolver wrapper feeding every solve (and every
    consumed batch simulation) to the auditor.  `__getattr__` forwards
    everything else (warmup, feature gates, delta feed wiring) so the
    controllers can't tell they're probed."""

    def __init__(self, inner, auditor: "TrajectoryAuditor",
                 world_lock: Optional[threading.RLock] = None):
        self._inner = inner
        self._auditor = auditor
        # shared with the rewind engine's event-apply loop: nothing may
        # mutate the cluster between the live solve's encode and the
        # drained oracle re-solve below, or the oracle judges a world
        # the live solve never saw
        self._world = world_lock if world_lock is not None \
            else threading.RLock()

    def solve(self, inp, source: str = "solver",
              max_nodes: Optional[int] = None):
        # the whole solve+audit window runs under the world lock, and
        # the shadow sampler drains BEFORE the caller acts on the
        # result: the sampler's oracle re-solve reads live cluster
        # objects through inp (ExistingNode.node taints, resident-pod
        # lists), and replay compresses hours of churn into seconds —
        # a pod marked deleting (or a node tainted) by the rewind
        # thread anywhere between the live encode and the async
        # worker's re-solve makes the oracle call the difference a
        # divergence.  Lock + drain pin the oracle to the exact state
        # the live solve encoded, so a diverged verdict during replay
        # is a real parity break, not a race artifact.
        with self._world:
            res = self._inner.solve(inp, source=source,
                                    max_nodes=max_nodes)
            self._auditor.on_solve(inp, res)
            from karpenter_tpu.solver.audit import SAMPLER, sample_rate
            if sample_rate() > 0.0:
                SAMPLER.drain(timeout=60.0)
        return res

    def solve_batch(self, inps, source: str = "disruption",
                    max_nodes: Optional[int] = None):
        # batch simulations are what-if probes (consolidation's
        # candidate axis), not committed placements: the atomicity /
        # inversion judges only score results a controller acts on, so
        # the batch passes through unprobed.
        return self._inner.solve_batch(inps, source=source,
                                       max_nodes=max_nodes)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TrajectoryAuditor:
    """Accumulates violations across a replay; `report()` renders the
    invariant booleans the bench record and the smoke gate assert."""

    def __init__(self):
        self._lock = threading.Lock()
        self.solves = 0
        self.gang_violations: List[dict] = []
        self.priority_inversions: List[dict] = []
        # expected pod universe, maintained by the rewind engine:
        # pod.add inserts, pod.remove (and observed completions the
        # engine itself initiates) discards
        self.expected_pods: set = set()

    # -- solve-level judges (called from SolveProbe, solver thread) ----
    def on_solve(self, inp, res) -> None:
        if res is None:
            return
        from karpenter_tpu.scheduling.types import (
            gang_placement_audit, priority_inversion_audit)
        with self._lock:
            self.solves += 1
        try:
            audit = gang_placement_audit(inp, res)
        except Exception:
            audit = {}
        for gname, entry in audit.items():
            bad = entry["placed"] not in (0, entry["total"])
            if not bad and entry["placed"] and \
                    entry["spec"].domain_key is not None:
                bad = bool(entry["unpinned"]) or len(entry["domains"]) > 1
            if bad:
                with self._lock:
                    self.gang_violations.append({
                        "gang": gname, "total": entry["total"],
                        "placed": entry["placed"],
                        "stranded": entry["stranded"],
                        "domains": sorted(map(str, entry["domains"])),
                        "unpinned": entry["unpinned"]})
        try:
            inversions = priority_inversion_audit(
                inp, res, getattr(res, "preemptions", ()) or ())
        except Exception:
            inversions = []
        if inversions:
            with self._lock:
                self.priority_inversions.extend(inversions)

    # -- trajectory-level judges ---------------------------------------
    @staticmethod
    def ledger_check(records: List[dict]) -> dict:
        """Hex-exact chain over ledger record dicts (ring tail or spill
        load): after == before + delta bit-for-bit, and the recorded
        cost_delta_hex round-trips its float."""
        broken = []
        checked = 0
        for r in records:
            delta = r.get("cost_delta")
            hexed = r.get("cost_delta_hex")
            if delta is not None and hexed and \
                    float(delta).hex() != hexed:
                broken.append({"seq": r.get("seq"),
                               "why": "cost_delta_hex mismatch"})
                continue
            before, after = r.get("fleet_cost_before"), \
                r.get("fleet_cost_after")
            if before is None or after is None or delta is None:
                continue
            checked += 1
            want = float(before) + float(delta)
            if float(after).hex() != want.hex():
                broken.append({"seq": r.get("seq"),
                               "why": "after != before + delta",
                               "after": float(after).hex(),
                               "want": want.hex()})
        return {"records": len(records), "checked": checked,
                "broken": broken, "exact": not broken}

    def lost_pods(self, cluster) -> List[str]:
        """Expected-universe reconciliation: every pod the timeline fed
        in and never removed must still exist in the cluster (pending
        OR scheduled — stranded is visible, vanished is the bug)."""
        with self._lock:
            expected = set(self.expected_pods)
        live = {p.meta.name for p in cluster.pods.list()}
        return sorted(expected - live)

    def report(self, cluster, ledger_records: List[dict],
               audit_deltas: Dict[str, int]) -> dict:
        ledger = self.ledger_check(ledger_records)
        lost = self.lost_pods(cluster)
        diverged = audit_deltas.get("diverged", 0)
        errored = audit_deltas.get("error", 0)
        with self._lock:
            gang = list(self.gang_violations)
            inv = list(self.priority_inversions)
            solves = self.solves
        return {
            "solves": solves,
            "ledger_hex_exact": ledger["exact"],
            "ledger_rows_checked": ledger["checked"],
            "ledger_breaks": ledger["broken"][:8],
            "zero_gang_atomicity_violations": not gang,
            "gang_violations": gang[:8],
            "zero_priority_inversions": not inv,
            "priority_inversions": inv[:8],
            "audit_clean": diverged == 0 and errored == 0,
            "audit_verdict_deltas": dict(audit_deltas),
            "zero_lost_pods": not lost,
            "lost_pods": lost[:16],
        }


def audit_series() -> Dict[str, float]:
    """Snapshot of the shadow-audit verdict counters, by verdict label
    — subtract two snapshots to get the replay's own deltas."""
    from karpenter_tpu.utils import metrics
    vals = getattr(metrics.SOLVER_AUDIT, "_values", None)
    if vals is None:
        return {}
    with metrics.SOLVER_AUDIT._lock:
        items = list(vals.items())
    return {"/".join(k) if k else "": v for k, v in items}


def audit_deltas(before: Dict[str, float],
                 after: Dict[str, float]) -> Dict[str, int]:
    return {k: int(after.get(k, 0) - before.get(k, 0))
            for k in sorted(set(before) | set(after))}


def state_digest(cluster, pricing=None) -> str:
    """Canonical sha256 of the cluster's schedulable state: sorted
    pods (name, node, phase), nodes (name, instance labels that matter
    to packing), claims (name, instance type, capacity type, zone,
    phase), plus the fleet $/hr in IEEE hex when pricing is given.
    Two replays that agree here reconstructed the SAME cluster —
    the seek/checkpoint bit-identity contract."""
    from karpenter_tpu.models import wellknown
    pods = sorted(
        (p.meta.name, p.node_name or "", p.phase)
        for p in cluster.pods.list())
    nodes = sorted(
        (n.meta.name,
         n.labels.get(wellknown.INSTANCE_TYPE_LABEL, ""),
         n.labels.get(wellknown.CAPACITY_TYPE_LABEL, ""),
         n.labels.get(wellknown.ZONE_LABEL, ""),
         bool(n.meta.deleting))
        for n in cluster.nodes.list())
    claims = sorted(
        (c.meta.name, c.nodepool, c.provider_id or "",
         c.node_name or "", bool(c.meta.deleting),
         tuple(sorted((k, bool(v)) for k, v in c.conditions.items())))
        for c in cluster.nodeclaims.list())
    payload = {"pods": pods, "nodes": nodes, "claims": claims}
    if pricing is not None:
        from karpenter_tpu.utils.ledger import fleet_cost
        payload["fleet_cost_hex"] = float(
            fleet_cost(cluster, pricing)["total"]).hex()
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()
