"""Timeline recorder: the cluster-mutation black box (ISSUE 17).

Where the flight recorder (utils/flightrecorder.py) answers "what did
ONE solve see and answer", the timeline recorder answers "what happened
to the CLUSTER, in order": every informer-cache mutation plus the
semantic drive events (spot reclaim, price refresh, fault injection,
gang/priority arrival) lands here as one monotonic record.  A spilled
timeline is replayable: `timeline/rewind.py` reconstructs the cluster
trajectory from the drive events and re-audits every invariant along
the way.

Knobs (env-resolved per record, same discipline as the flight ring):

  KARPENTER_TPU_TIMELINE=off|0       disable (default: on — the ring
                                     append is O(1) and the spill only
                                     runs when a directory is set)
  KARPENTER_TPU_TIMELINE_BUFFER=N    ring size (default 4096 — a
                                     timeline is much chattier than the
                                     solve ring)
  KARPENTER_TPU_TIMELINE_DIR=<dir>   spill each event as one JSONL line
                                     to <dir>/timeline-<pid>.jsonl

Cross-links stamped on every record: the active trace id, the flight
recorder's newest solve seq, and the decision ledger's newest row seq —
so any timeline event can be joined to the solve that preceded it and
the ledger row it produced.  The spill loader is
`flightrecorder.load_records` (shared torn-line-tolerant code path —
its truncation coverage in tests/test_flight.py covers this file too).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import List, Optional

from karpenter_tpu.timeline import events as ev
from karpenter_tpu.utils import flightrecorder, metrics, tracing

_ENV_GATE = "KARPENTER_TPU_TIMELINE"
_ENV_BUFFER = "KARPENTER_TPU_TIMELINE_BUFFER"
_ENV_DIR = "KARPENTER_TPU_TIMELINE_DIR"


def recording_enabled() -> bool:
    """On unless explicitly disabled — same always-on posture as the
    flight ring; the default path is a lock + deque append."""
    from karpenter_tpu.utils.knobs import env_bool
    return env_bool(_ENV_GATE, default=True)


class TimelineEvent:
    __slots__ = ("seq", "ts", "pid", "kind", "name", "data",
                 "trace_id", "flight_seq", "ledger_seq")

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw.get(name))

    def to_dict(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


class TimelineRecorder:
    """Bounded ring + optional JSONL spill; one per process
    (module-level RECORDER), thread-safe — controllers, the operator
    loop, and the dashboard reader all touch it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._buffer_size())
        self._seq = 0
        self._spill = None          # (path, file handle) once opened
        self._spill_failed = False  # one strike, then best-effort off
        # first-member markers: one gang.arrival / priority.arrival per
        # distinct gang name / priority band per process lifetime
        self._seen_gangs: set = set()
        self._seen_priorities: set = set()

    @staticmethod
    def _buffer_size() -> int:
        try:
            return max(1, int(os.environ.get(_ENV_BUFFER, "4096")))
        except ValueError:
            return 4096

    @property
    def enabled(self) -> bool:
        return recording_enabled()

    def emit(self, kind: str, name: str = "",
             data: Optional[dict] = None) -> Optional[TimelineEvent]:
        if not self.enabled:
            return None
        from karpenter_tpu.utils.ledger import LEDGER
        with self._lock:
            self._seq += 1
            rec = TimelineEvent(
                # capture-side provenance stamp: replay rebases ts (the
                # engine's clock drives ticks) and digests exclude it
                seq=self._seq, ts=time.time(), pid=os.getpid(),  # kt-lint: disable=nondeterminism-source
                kind=kind, name=name, data=data,
                trace_id=tracing.current_trace_id(),
                flight_seq=flightrecorder.RECORDER.last_seq(),
                ledger_seq=LEDGER.last_seq())
            self._ring.append(rec)
        metrics.TIMELINE_EVENTS.inc(kind=kind)
        self._maybe_spill(rec)
        return rec

    def _maybe_spill(self, rec: TimelineEvent) -> None:
        d = os.environ.get(_ENV_DIR)
        if not d or self._spill_failed:
            return
        import json
        line = json.dumps(rec.to_dict(), default=str)
        try:
            with self._lock:
                path = os.path.join(d, f"timeline-{os.getpid()}.jsonl")
                if self._spill is None or self._spill[0] != path:
                    os.makedirs(d, exist_ok=True)
                    if self._spill is not None:
                        self._spill[1].close()
                    self._spill = (path, open(path, "a", encoding="utf-8"))
                f = self._spill[1]
                f.write(line + "\n")
                f.flush()
        except OSError:
            # best-effort, like the flight spill: a full disk degrades
            # the timeline to ring-only, never fails a controller —
            # but counted, so restart replay losing events is visible
            metrics.SPILL_DEGRADED.inc(recorder="timeline")
            self._spill_failed = True

    def tail(self, n: int = 64, kind: Optional[str] = None,
             since: Optional[int] = None) -> List[dict]:
        if n <= 0:
            return []  # recs[-0:] would be the whole ring, not nothing
        with self._lock:
            recs = list(self._ring)
        if kind is not None:
            recs = [r for r in recs if r.kind == kind]
        if since is not None:
            recs = [r for r in recs if r.seq > since]
        return [r.to_dict() for r in recs[-n:]]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def last_seq(self) -> Optional[int]:
        with self._lock:
            return self._seq if self._seq else None

    def reset(self) -> None:
        """Clear the ring and close any spill handle (tests)."""
        with self._lock:
            self._ring = deque(maxlen=self._buffer_size())
            self._seq = 0
            self._seen_gangs = set()
            self._seen_priorities = set()
            if self._spill is not None:
                try:
                    self._spill[1].close()
                except OSError:
                    pass
            self._spill = None
            self._spill_failed = False


RECORDER = TimelineRecorder()


def emit(kind: str, name: str = "",
         data: Optional[dict] = None) -> Optional[TimelineEvent]:
    """Module-level convenience over RECORDER.emit — the one call site
    shape the kt-lint registry gate watches for literal kinds."""
    return RECORDER.emit(kind, name=name, data=data)


def pod_spec(pod) -> dict:
    """The replayable slice of a pod: dense request vector plus the
    metadata the solver's semantics depend on (gang/priority/topology
    annotations, labels).  `rewind.make_pod` inverts this."""
    meta = pod.meta
    return {
        "requests": list(getattr(pod.requests, "v", []) or []),
        "annotations": dict(getattr(meta, "annotations", {}) or {}),
        "labels": dict(getattr(meta, "labels", {}) or {}),
    }


def record_store_mutation(cluster, kind: str, op: str, name: str) -> None:
    """The `Cluster.mutated` hook: one `store.<kind>.<op>` observation
    per informer-cache mutation, plus the semantic first-member markers
    (gang.arrival / priority.arrival) on pod arrival.  Pod additions
    carry the replayable spec so a recorded stream can be promoted to
    drive events."""
    if not RECORDER.enabled or not kind:
        return
    data = None
    if kind == "pods" and op == "added":
        pod = cluster.pods.get(name)
        if pod is not None:
            data = pod_spec(pod)
            _semantic_markers(name, data["annotations"])
    emit(ev.store_event(kind, op), name=name, data=data)


def _semantic_markers(pod_name: str, annotations: dict) -> None:
    """gang.arrival on the first member of each gang, priority.arrival
    on the first pod of each non-default priority band — the scenario
    bookmarks the ISSUE's 'priority/gang arrival' capture asks for."""
    from karpenter_tpu.models import wellknown
    gname = annotations.get(wellknown.GANG_NAME_ANNOTATION)
    if gname:
        with RECORDER._lock:
            fresh = gname not in RECORDER._seen_gangs
            RECORDER._seen_gangs.add(gname)
        if fresh:
            emit(ev.GANG_ARRIVAL, name=gname, data={
                "first_member": pod_name,
                "size": annotations.get(
                    wellknown.GANG_SIZE_ANNOTATION),
                "topology": annotations.get(
                    wellknown.GANG_TOPOLOGY_ANNOTATION)})
    prio = annotations.get(wellknown.PRIORITY_ANNOTATION)
    if prio:
        with RECORDER._lock:
            fresh = prio not in RECORDER._seen_priorities
            RECORDER._seen_priorities.add(prio)
        if fresh:
            emit(ev.PRIORITY_ARRIVAL, name=str(prio),
                 data={"first_pod": pod_name})


def load_events(path: str) -> List[dict]:
    """Parse one spilled timeline-<pid>.jsonl — or stitch every
    timeline-*.jsonl under a directory in (mtime, name) order, the
    multi-process / restart-replay case (ROADMAP item 5).  Delegates to
    the flight recorder's torn-line-tolerant loader — the shared code
    path the ISSUE pins: a crashed process leaves at most one torn tail
    line, and every record before it must load."""
    return [r for r in flightrecorder.load_records(path, prefix="timeline")
            if isinstance(r, dict) and "kind" in r]
