"""Leader election — the active/passive replica story (VERDICT r2 #8).

The reference deploys 2 replicas with leader election on a coordination
lease (charts/karpenter/values.yaml:35; core LEADER_ELECT, settings.md):
one replica reconciles, the standby takes over when the lease expires.
Same shape here: a `LeaderElector` per replica races `try_acquire_or_renew`
against a shared lease backend.

Backends:
  * `InMemoryLease` — replicas in one process (tests, embedded pairs).
  * `FileLease` — replicas on one host sharing a lease file; mutual
    exclusion via flock so acquire is atomic across processes. Replicas
    sharing one host is exactly the deployment `kt_solverd` enables (one
    TPU-owning daemon, N control planes — native/solverd.cc).

Timing mirrors client-go's LeaderElectionConfig defaults scaled down
(leaseDuration 15s / renewDeadline 10s / retryPeriod 2s).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class InMemoryLease:
    """A process-local lease shared by reference between replicas."""

    def __init__(self):
        self._lock = threading.Lock()
        self._holder: Optional[str] = None
        self._expiry: float = 0.0

    def try_acquire(self, identity: str, duration: float,
                    now: float) -> bool:
        with self._lock:
            if self._holder in (None, identity) or now >= self._expiry:
                self._holder = identity
                self._expiry = now + duration
                return True
            return False

    def release(self, identity: str) -> None:
        with self._lock:
            if self._holder == identity:
                self._holder = None
                self._expiry = 0.0

    def holder(self, now: float) -> Optional[str]:
        with self._lock:
            return self._holder if now < self._expiry else None


class FileLease:
    """A lease file shared by replicas on one host.

    The read-check-write critical section runs under flock on a sidecar
    lock file, so two processes can't both see an expired lease and both
    write themselves as holder. Timestamps are wall-clock (shared between
    processes; monotonic clocks are not)."""

    def __init__(self, path: str):
        self.path = path
        self._lock_path = path + ".lock"

    # bounded so a wedged peer process holding the flock demotes this
    # replica (try_acquire returns False, the elector stays standby and
    # retries) instead of freezing its run loop on an unbounded LOCK_EX
    # wait (kt-lint lock-discipline)
    FLOCK_TIMEOUT = 0.5

    def _with_flock(self, fn):
        """Run `fn` under the sidecar flock; returns None (without running
        `fn`) when the flock stays contended past FLOCK_TIMEOUT."""
        import fcntl
        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            deadline = time.monotonic() + self.FLOCK_TIMEOUT
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except OSError:
                    if time.monotonic() >= deadline:
                        return None
                    time.sleep(0.01)
            try:
                return fn()
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)

    def _read(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _write(self, rec: dict) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)

    def try_acquire(self, identity: str, duration: float,
                    now: float) -> bool:
        def attempt():
            rec = self._read()
            if rec.get("holder") in (None, identity) \
                    or now >= rec.get("expiry", 0.0):
                self._write({"holder": identity, "expiry": now + duration})
                return True
            return False
        # contended flock (None) = another replica is mid-acquire: report
        # not-acquired; the elector retries on its retry_period cadence
        return bool(self._with_flock(attempt))

    def release(self, identity: str) -> None:
        def attempt():
            rec = self._read()
            if rec.get("holder") == identity:
                self._write({})
        self._with_flock(attempt)

    def holder(self, now: float) -> Optional[str]:
        rec = self._read()
        return rec.get("holder") if now < rec.get("expiry", 0.0) else None


class LeaderElector:
    """Per-replica election state machine.

    `try_acquire_or_renew()` is called from the replica's run loop: the
    leader renews every `renew_interval`, a standby retries acquisition
    every `retry_period`. Losing the lease (renewal raced an expiry
    takeover) demotes back to standby — the replica keeps running and may
    re-acquire later, unlike client-go's process exit, because our
    controllers are idempotent against the shared store."""

    def __init__(self, lease, identity: Optional[str] = None,
                 lease_duration: float = 15.0, renew_interval: float = 5.0,
                 retry_period: float = 2.0, now=time.time):
        import uuid
        self.lease = lease
        # nodename-pid alone collides for two replicas in one process (the
        # InMemoryLease use case) and holder==identity counts as a renew —
        # a per-instance nonce keeps default identities unique
        self.identity = identity or (
            f"{os.uname().nodename}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.retry_period = retry_period
        self._now = now
        self._is_leader = False
        self._last_renew = 0.0

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def try_acquire_or_renew(self) -> bool:
        """Returns leadership after this attempt; renews at most every
        renew_interval while leading."""
        now = self._now()
        if self._is_leader and now - self._last_renew < self.renew_interval:
            return True
        ok = self.lease.try_acquire(self.identity, self.lease_duration, now)
        if ok:
            self._last_renew = now
        was = self._is_leader
        self._is_leader = ok
        if was and not ok:
            # lost the lease — another replica took over during our gap
            self._last_renew = 0.0
        return ok

    def release(self) -> None:
        if self._is_leader:
            self.lease.release(self.identity)
            self._is_leader = False
