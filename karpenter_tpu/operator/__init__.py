"""Operator: options, feature gates, and the wiring of providers +
controllers (reference: pkg/operator + pkg/operator/options)."""

from karpenter_tpu.operator.options import Options, FeatureGates

__all__ = ["Options", "FeatureGates"]
