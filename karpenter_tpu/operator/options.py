"""Options + feature gates.

Mirrors the reference's layered flag/env config (pkg/operator/options;
settings documented at website/.../settings.md). The TPU solver toggle is a
feature gate exactly like the reference's FEATURE_GATES string
(`SpotToSpotConsolidation=true` style — SURVEY §5 config/flag system).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict


@dataclass
class FeatureGates:
    tpu_solver: bool = True            # TPUSolver=true: device hot path on
    spot_to_spot_consolidation: bool = True
    drift: bool = True

    @classmethod
    def parse(cls, s: str) -> "FeatureGates":
        """FEATURE_GATES=TPUSolver=true,Drift=false"""
        gates = cls()
        mapping = {
            "TPUSolver": "tpu_solver",
            "SpotToSpotConsolidation": "spot_to_spot_consolidation",
            "Drift": "drift",
        }
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            attr = mapping.get(key)
            if attr is not None:
                setattr(gates, attr, val.strip().lower() == "true")
        return gates


@dataclass
class Options:
    cluster_name: str = "default-cluster"
    # pod batching window (settings.md BATCH_IDLE_DURATION / BATCH_MAX_DURATION)
    batch_idle_duration: float = 1.0
    batch_max_duration: float = 10.0
    # lifecycle
    registration_ttl: float = 15 * 60.0   # never-registered GC (designs/limits.md:23-25)
    # solver
    solver_max_nodes: int = 1024
    # multi-chip: "auto" shards the solve's column axis over every local
    # device when >1 is visible (SURVEY §2.3 ICI sharding); "off" forces
    # single-device; an integer uses the first n devices
    solver_mesh: str = "auto"
    # incremental delta solves (solver/delta.py): "auto" engages on
    # steady-state repeats above the min-size gate, "on" forces, "off"
    # disables. KARPENTER_TPU_DELTA is the rollback override, resolved
    # inside the solver exactly like KARPENTER_TPU_MESH.
    solver_delta: str = "auto"
    # unix-socket path of a kt_solverd solver service (native/solverd.cc);
    # None = in-process solver. Lets control-plane replicas share one
    # TPU-owning process (SURVEY §2.3 leader-election note).
    solver_endpoint: "str | None" = None
    # solver-service availability knobs (service/resilience.py): one
    # request deadline (also shipped in the frame so the daemon sheds
    # work its caller abandoned), bounded retries, and the circuit
    # breaker that puts the control plane into explicit degraded mode
    # (in-process solver, then oracle) when the daemon is down/wedged
    service_request_timeout: float = 60.0
    service_retry_attempts: int = 3
    service_breaker_threshold: int = 5
    service_breaker_cooldown: float = 10.0
    # degraded mode: while the breaker is open (or any remote solve
    # fails), fall back to a lazily-built in-process TPUSolver before
    # the host oracle. Disable to keep the old endpoint->oracle-only
    # behavior (e.g. a control-plane host too small for a solver).
    service_local_fallback: bool = True
    # multi-tenant solver fleet (ISSUE 11): the tenant name this control
    # plane declares on every schedule frame (None = cluster_name — one
    # cluster, one tenant), and the admission-control priority rank (the
    # daemon sheds lowest priority first when a tenant queue is full)
    service_tenant: "str | None" = None
    service_priority: int = 0
    # HA: active/passive replicas racing a shared lease (core LEADER_ELECT;
    # charts/karpenter/values.yaml:35 runs 2 replicas). lease_file names a
    # FileLease shared by replicas on one host.
    leader_elect: bool = False
    lease_file: "str | None" = None
    feature_gates: FeatureGates = field(default_factory=FeatureGates)

    @classmethod
    def from_env(cls) -> "Options":
        opts = cls()
        opts.cluster_name = os.environ.get("CLUSTER_NAME", opts.cluster_name)
        if "BATCH_IDLE_DURATION" in os.environ:
            opts.batch_idle_duration = float(os.environ["BATCH_IDLE_DURATION"])
        if "BATCH_MAX_DURATION" in os.environ:
            opts.batch_max_duration = float(os.environ["BATCH_MAX_DURATION"])
        if "FEATURE_GATES" in os.environ:
            opts.feature_gates = FeatureGates.parse(os.environ["FEATURE_GATES"])
        opts.solver_endpoint = os.environ.get(
            "SOLVER_ENDPOINT", opts.solver_endpoint)
        if "KARPENTER_TPU_SERVICE_TIMEOUT" in os.environ:
            opts.service_request_timeout = float(
                os.environ["KARPENTER_TPU_SERVICE_TIMEOUT"])
        if "KARPENTER_TPU_SERVICE_RETRIES" in os.environ:
            opts.service_retry_attempts = int(
                os.environ["KARPENTER_TPU_SERVICE_RETRIES"])
        if "KARPENTER_TPU_SERVICE_BREAKER_THRESHOLD" in os.environ:
            opts.service_breaker_threshold = int(
                os.environ["KARPENTER_TPU_SERVICE_BREAKER_THRESHOLD"])
        if "KARPENTER_TPU_SERVICE_BREAKER_COOLDOWN" in os.environ:
            opts.service_breaker_cooldown = float(
                os.environ["KARPENTER_TPU_SERVICE_BREAKER_COOLDOWN"])
        # canonical symmetric on/off grammar (utils/knobs.py); malformed
        # values degrade to the default (on) — an operator must opt OUT
        # of the fallback explicitly, never via a typo
        from karpenter_tpu.utils.knobs import env_bool
        opts.service_local_fallback = env_bool(
            "KARPENTER_TPU_SERVICE_LOCAL_FALLBACK",
            default=opts.service_local_fallback)
        opts.service_tenant = os.environ.get(
            "KARPENTER_TPU_TENANT", opts.service_tenant)
        # renamed from KARPENTER_TPU_PRIORITY (ISSUE 16): that name now
        # belongs to the POD-priority scheduling rollback lever
        # (utils/knobs.py); this one ranks the control plane's own
        # requests in the solver daemon's admission queue
        if "KARPENTER_TPU_SERVICE_PRIORITY" in os.environ:
            opts.service_priority = int(
                os.environ["KARPENTER_TPU_SERVICE_PRIORITY"])
        # SOLVER_MESH configures the mesh story.  The KARPENTER_TPU_MESH
        # rollback override is deliberately NOT parsed here: its single
        # grammar owner is TPUSolver._mesh_env_spec, applied inside
        # _resolve_mesh so it reaches every solver however built —
        # including the one state.py constructs from this options value
        opts.solver_mesh = os.environ.get("SOLVER_MESH", opts.solver_mesh)
        # SOLVER_DELTA configures the delta-solve story; the
        # KARPENTER_TPU_DELTA rollback override is deliberately NOT
        # parsed here — its single grammar owner is
        # TPUSolver._delta_env_spec (same discipline as the mesh knob)
        opts.solver_delta = os.environ.get("SOLVER_DELTA",
                                           opts.solver_delta)
        opts.leader_elect = os.environ.get(
            "LEADER_ELECT", "").strip().lower() in ("1", "true", "yes")
        opts.lease_file = os.environ.get("LEASE_FILE", opts.lease_file)
        return opts
