"""The runnable operator process.

Mirrors the reference's boot sequence (cmd/controller/main.go:31-74 →
pkg/operator/operator.go:92-200): construct the cloud session, probe
connectivity, build every provider and controller, serve metrics and
health endpoints, then run the manager until signalled.  The provider
wiring itself lives in `karpenter_tpu.env.Environment` (the reference
splits the same construction between operator.go:140-182 and
pkg/test/environment.go — ours is one container used by both the process
and the tests, so they can never drift apart).

Endpoints (settings.md: metrics :8000, health probe :8081):
  :8000 /metrics  — Prometheus text exposition of utils.metrics.REGISTRY
  :8081 /healthz  — liveness: the reconcile loop is advancing
  :8081 /readyz   — readiness: CloudProvider.live() (the aggregated
                    provider probe chain, cloudprovider.go:167-169)
"""

from __future__ import annotations

import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu.env import Environment
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.clock import RealClock


class Operator:
    """Owns the Environment, the serving threads, and the reconcile loop."""

    def __init__(self, options: Optional[Options] = None,
                 metrics_port: int = 8000, health_port: int = 8081,
                 reconcile_interval: float = 1.0,
                 env: Optional[Environment] = None, lease=None,
                 identity: Optional[str] = None):
        self.options = options or Options.from_env()
        # env is injectable so an HA pair (or a test) can run two replicas
        # against one shared cluster store, the way two reference replicas
        # share the kube-apiserver
        self.env = env or Environment(clock=RealClock(), options=self.options)
        self.metrics_port = metrics_port
        self.health_port = health_port
        self.reconcile_interval = reconcile_interval
        self._stop = threading.Event()
        self._last_reconcile = 0.0
        self._servers: list = []
        self.elector = None
        # leadership signal: SET while this replica holds the lease.  The
        # dedicated renewal thread (started by run()) owns the elector;
        # the reconcile loop only reads this event, so a long solve can
        # never starve renewal past lease_duration (the historical
        # dual-leader flake in test_ha)
        self._leadership = threading.Event()
        self._renewer: Optional[threading.Thread] = None
        if self.options.leader_elect or lease is not None:
            from karpenter_tpu.operator.leaderelection import (
                FileLease,
                LeaderElector,
            )
            if lease is None:
                if not self.options.lease_file:
                    raise ValueError("leader_elect requires lease_file")
                lease = FileLease(self.options.lease_file)
            self.elector = LeaderElector(lease, identity=identity)
        # boot-time connectivity probe, the reference's CheckEC2Connectivity
        # (operator.go:209-218): fail fast if the cloud isn't reachable
        if not self.env.cloud.live():
            raise RuntimeError("cloud connectivity probe failed at startup")
        # build the native host-ops extension now, not inside a solve
        from karpenter_tpu.native import hostops
        hostops()
        # profiler server behind ENABLE_PROFILING (the reference gates
        # pprof the same way, settings.md:23; ours serves JAX/XLA traces)
        from karpenter_tpu.utils.logging import get_logger
        from karpenter_tpu.utils.profiling import maybe_start_server
        self.log = get_logger("operator")
        maybe_start_server(log=lambda m: self.log.info(m))

    # -- HTTP endpoints ----------------------------------------------------
    def _make_handler(operator_self):  # noqa: N805 - closure over operator
        op = operator_self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet by default
                pass

            def _respond(self, code: int, body: str,
                         ctype: str = "text/plain; charset=utf-8"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 - stdlib API
                from urllib.parse import parse_qs, urlparse
                url = urlparse(self.path)
                path = url.path
                if path == "/metrics":
                    self._respond(200, metrics.REGISTRY.render(),
                                  "text/plain; version=0.0.4")
                elif path == "/healthz":
                    # live while the loop has run recently (3 intervals of
                    # grace covers a long solve) or hasn't started yet
                    stale = (op._last_reconcile > 0 and
                             time.monotonic() - op._last_reconcile >
                             max(30.0, 3 * op.reconcile_interval +
                                 op.options.batch_max_duration))
                    self._respond(503 if stale else 200,
                                  "unhealthy\n" if stale else "ok\n")
                elif path == "/readyz":
                    ready = op.env.cloud_provider.live()
                    self._respond(200 if ready else 503,
                                  "ok\n" if ready else "not ready\n")
                elif path == "/debug/traces":
                    # recent completed traces as Chrome trace-event JSON
                    # (Perfetto / chrome://tracing loadable); ?trace_id=
                    # narrows to one — the id an event or log line
                    # stamped; ?limit= caps the trace count so a large
                    # ring never dumps unbounded JSON.  The export also
                    # carries otherData.spansDropped (the collector's
                    # eviction counter).
                    from karpenter_tpu.utils import tracing
                    q = parse_qs(url.query)
                    tid = (q.get("trace_id") or [None])[0]
                    try:
                        limit = int((q.get("limit") or [""])[0])
                    except ValueError:
                        limit = None
                    self._respond(
                        200,
                        json.dumps(tracing.chrome_trace(tid, limit)) +
                        "\n", "application/json; charset=utf-8")
                elif path == "/debug/dashboard":
                    # the ONE merged fleet view (utils/telemetry.py):
                    # operator + registered sources (supervisor) + the
                    # solverd worker via its stats RPC; ?format=html for
                    # the no-tooling rendering
                    from karpenter_tpu.utils import telemetry
                    doc = telemetry.collect(
                        extra={"worker": op._worker_snapshot})
                    fmt = (parse_qs(url.query).get("format")
                           or ["json"])[0]
                    if fmt == "html":
                        self._respond(200, telemetry.render_html(doc),
                                      telemetry.HTML_CONTENT_TYPE)
                    else:
                        self._respond(
                            200, json.dumps(doc, default=str) + "\n",
                            "application/json; charset=utf-8")
                elif path == "/debug/ledger":
                    # the decision ledger (utils/ledger.py): every
                    # fleet-mutating decision with before/after $/hr,
                    # reason code, and trace/flight cross-links.
                    # ?pool= narrows to one nodepool, ?since=<unix ts>
                    # to a window, ?limit= caps the count (default 64);
                    # ?format=html renders the no-tooling view.  The
                    # summary block is ledger.summarize over EXACTLY the
                    # returned records, the same rollup tools/
                    # kt_ledger.py prints — the two surfaces cannot
                    # disagree.
                    from karpenter_tpu.utils import ledger as ledgerm
                    from karpenter_tpu.utils import telemetry
                    q = parse_qs(url.query)
                    pool = (q.get("pool") or [None])[0]
                    try:
                        limit = int((q.get("limit") or ["64"])[0])
                    except ValueError:
                        limit = 64
                    try:
                        since = float((q.get("since") or [""])[0])
                    except ValueError:
                        since = None
                    records = ledgerm.LEDGER.tail(limit, pool=pool,
                                                  since=since)
                    doc = {"records": records,
                           "summary": ledgerm.summarize(records)}
                    fmt = (q.get("format") or ["json"])[0]
                    if fmt == "html":
                        self._respond(
                            200,
                            telemetry.html_page(
                                "karpenter-tpu decision ledger",
                                [("summary", doc["summary"]),
                                 ("records", records)]),
                            telemetry.HTML_CONTENT_TYPE)
                    else:
                        self._respond(
                            200, json.dumps(doc, default=str) + "\n",
                            "application/json; charset=utf-8")
                elif path == "/debug/flight":
                    # the flight-recorder tail (request records);
                    # ?trace_id= narrows to the records of one trace,
                    # ?limit= caps the count (default 32)
                    from karpenter_tpu.utils import flightrecorder
                    q = parse_qs(url.query)
                    tid = (q.get("trace_id") or [None])[0]
                    try:
                        limit = int((q.get("limit") or ["32"])[0])
                    except ValueError:
                        limit = 32
                    self._respond(
                        200,
                        json.dumps({"records": flightrecorder.RECORDER
                                    .tail(limit, trace_id=tid)},
                                   default=str) + "\n",
                        "application/json; charset=utf-8")
                elif path == "/debug/timeline":
                    # the cluster timeline tail (timeline/recorder.py):
                    # every informer-cache mutation + semantic drive
                    # event with trace/flight/ledger cross-links.
                    # ?kind= narrows to one event kind, ?since=<seq>
                    # to events after a sequence number, ?limit= caps
                    # the count (default 64); ?format=html renders the
                    # no-tooling view.
                    from karpenter_tpu import timeline
                    from karpenter_tpu.timeline import events as tev
                    from karpenter_tpu.utils import telemetry
                    q = parse_qs(url.query)
                    kind = (q.get("kind") or [None])[0]
                    try:
                        limit = int((q.get("limit") or ["64"])[0])
                    except ValueError:
                        limit = 64
                    try:
                        since = int((q.get("since") or [""])[0])
                    except ValueError:
                        since = None
                    evts = timeline.RECORDER.tail(limit, kind=kind,
                                                  since=since)
                    doc = {"events": evts,
                           "last_seq": timeline.RECORDER.last_seq(),
                           "kinds": tev.KINDS}
                    fmt = (q.get("format") or ["json"])[0]
                    if fmt == "html":
                        self._respond(
                            200,
                            telemetry.html_page(
                                "karpenter-tpu cluster timeline",
                                [("events", evts)]),
                            telemetry.HTML_CONTENT_TYPE)
                    else:
                        self._respond(
                            200, json.dumps(doc, default=str) + "\n",
                            "application/json; charset=utf-8")
                elif path == "/debug/explain":
                    # placement provenance (ISSUE 13): the per-pod
                    # constraint-elimination tree behind a FailedScheduling
                    # verdict.  ?pod= looks one pod up (?trace_id= pins a
                    # specific pass), no pod lists the recent stranded
                    # pods; ?format=html renders the no-tooling view.
                    from karpenter_tpu.solver import explain as explainm
                    q = parse_qs(url.query)
                    pod = (q.get("pod") or [None])[0]
                    tid = (q.get("trace_id") or [None])[0]
                    try:
                        limit = int((q.get("limit") or ["32"])[0])
                    except ValueError:
                        limit = 32
                    if pod:
                        entry = explainm.STORE.lookup(pod, trace_id=tid)
                        code = 200 if entry is not None else 404
                        doc = entry if entry is not None else {
                            "error": f"no explain record for pod {pod!r}"
                                     + (f" on trace {tid}" if tid else ""),
                            "hint": "the store holds recent provisioning "
                                    "verdicts; for a past solve, replay "
                                    "its flight record with "
                                    "tools/kt_explain.py"}
                    else:
                        code = 200
                        doc = {"pods": explainm.STORE.recent(limit),
                               "reason_codes": explainm.reason_table()}
                    fmt = (q.get("format") or ["json"])[0]
                    if fmt == "html":
                        from karpenter_tpu.utils import telemetry
                        self._respond(code, op._explain_html(doc),
                                      telemetry.HTML_CONTENT_TYPE)
                    else:
                        self._respond(
                            code, json.dumps(doc, default=str) + "\n",
                            "application/json; charset=utf-8")
                elif path == "/debug/state":
                    c = op.env.cluster
                    self._respond(200, json.dumps({
                        "generation": c.generation,
                        "nodes": len(c.nodes.list()),
                        "nodeclaims": len(c.nodeclaims.list()),
                        "pods": len(c.pods.list()),
                    }) + "\n", "application/json")
                else:
                    self._respond(404, "not found\n")

        return Handler

    @staticmethod
    def _explain_html(doc: dict) -> str:
        """The no-tooling rendering of one explain document — through
        the ONE shared page renderer (utils/telemetry.html_page), the
        same styling/escaping as the dashboard and ledger pages."""
        from karpenter_tpu.utils import telemetry
        title = doc.get("pod", "placement explainability")
        return telemetry.html_page(f"explain: {title}", [(None, doc)])

    def _worker_snapshot(self):
        """The solverd worker's section of the dashboard merge: its
        stats RPC response (which carries the worker-process telemetry
        snapshot — solve rate, phase latencies, delta split, flight
        tail) plus the client-side in-flight and breaker view only this
        process knows.  None in the in-process-solver topology (no
        worker to ask); raises on a dead worker and telemetry.collect
        renders the error — the dashboard must keep serving exactly
        when the fleet is degraded."""
        gs = getattr(self.env, "solver", None)
        client = getattr(gs, "tpu", None)
        if not getattr(gs, "_remote", False) or client is None:
            return None
        st = client.stats()
        snap = dict(st.pop("telemetry", None) or {})
        snap["stats"] = st
        snap["shed"] = st.get("shed", 0)
        snap["in_flight"] = len(client._pending)
        snap["breaker"] = client.breaker.state
        return snap

    def serve(self) -> None:
        if self._servers:
            return
        handler = self._make_handler()
        ports = []
        for port in (self.metrics_port, self.health_port):
            # loopback by default; a containerized replica sets
            # KARPENTER_TPU_BIND_HOST=0.0.0.0 so published ports and
            # healthchecks actually reach the server (deploy/)
            from karpenter_tpu.utils.knobs import bind_host
            host = bind_host()
            srv = ThreadingHTTPServer((host, port), handler)
            ports.append(srv.server_address[1])  # resolves port 0 → actual
            t = threading.Thread(target=srv.serve_forever, daemon=True,
                                 name=f"http-{srv.server_address[1]}")
            t.start()
            self._servers.append(srv)
        self.metrics_port, self.health_port = ports

    # -- leader election ---------------------------------------------------
    def _renew_loop(self) -> None:
        """Dedicated lease-renewal heartbeat.  Renewal used to run inline
        with reconcile, so one long pass (a cold solve compiling under
        XLA) starved the renew past lease_duration and the standby took
        over while the old leader was still mutating — the test_ha
        flake.  This thread is the elector's ONLY caller after run()
        starts; the reconcile loop consumes `_leadership` (set = this
        replica holds the lease) and never touches the lease itself."""
        e = self.elector
        while not self._stop.is_set():
            if e.try_acquire_or_renew():
                self._leadership.set()
                self._stop.wait(e.renew_interval / 2)
            else:
                self._leadership.clear()
                self._stop.wait(e.retry_period)
        self._leadership.clear()

    # -- the reconcile loop ------------------------------------------------
    def run(self) -> None:
        """manager.Start: WATCH-DRIVEN reconcile with periodic resync,
        matching controller-runtime's informer model — a store mutation
        (pod created, claim updated, node deleted) wakes the loop
        immediately instead of waiting out the poll cadence; with no
        events, the loop still resyncs every `reconcile_interval` so
        clock-driven work (batch windows, TTLs, GC) keeps advancing.
        Controllers are level-driven and idempotent, so coalesced or
        dropped watch edges are harmless."""
        self.serve()
        watch = self.env.cluster.watch()
        if self.elector is not None and self._renewer is None:
            self._renewer = threading.Thread(
                target=self._renew_loop, daemon=True,
                name=f"lease-renew-{self.elector.identity}")
            self._renewer.start()
        try:
            while not self._stop.is_set():
                if self.elector is not None \
                        and not self._leadership.is_set():
                    # standby: hold position; the renewal thread races
                    # the lease on its own cadence and flips
                    # `_leadership` the moment it wins, which ends this
                    # wait immediately (event-driven takeover, not a
                    # poll). Liveness stays green (the loop IS
                    # advancing). Drain so a takeover starts fresh.
                    watch.drain()
                    self._last_reconcile = time.monotonic()
                    self._leadership.wait(self.elector.retry_period)
                    continue
                t0 = time.monotonic()
                # run to a BOUNDED fixed point per wake: reconcile chains
                # (pod → claim → launch → register → bind) span several
                # passes, each advancing on the previous one's mutations
                for _ in range(8):
                    gen = self.env.cluster.generation
                    self.env.manager.run_once()
                    self._last_reconcile = time.monotonic()
                    if self.env.cluster.generation == gen or self._stop.is_set():
                        break
                    # the renewal thread keeps the lease fresh during a
                    # long fixed point; stop mutating the moment it
                    # reports the lease lost
                    if self.elector is not None \
                            and not self._leadership.is_set():
                        break
                # drain AFTER the fixed point: mutations made by the
                # reconcile itself (self-requeue patterns like the
                # lifecycle's ICE retry, which deliberately never settles
                # while capacity is short) must not wake the loop into a
                # zero-delay hot spin — they get the resync cadence, the
                # reference's workqueue-backoff analogue. An external edge
                # racing the reconcile is drained too; level-driven
                # controllers + resync cover it (informer discipline).
                watch.drain()
                elapsed = time.monotonic() - t0
                remaining = max(0.0, self.reconcile_interval - elapsed)
                # wake early on any store mutation; cap waits so stop()
                # and demotion stay responsive
                deadline = time.monotonic() + remaining
                while not self._stop.is_set():
                    if self.elector is not None \
                            and not self._leadership.is_set():
                        break  # demoted while idle → standby wait above
                    left = deadline - time.monotonic()
                    if left <= 0 or watch.wait(timeout=min(left, 0.25)):
                        break
                    # peer replicas' writes arrive via the store backend,
                    # not the local watch — apply them on the wait tick so
                    # a pod created through another replica wakes this
                    # loop with informer latency (applying publishes to
                    # the local watch, which the next wait() observes)
                    self.env.cluster.sync_backend()
        finally:
            self.env.cluster.unwatch(watch)
            # order matters: stop the renewal thread BEFORE releasing, or
            # it re-acquires the lease we just gave up
            self._stop.set()
            if self._renewer is not None:
                self._renewer.join(timeout=5)
            if self.elector is not None:
                self.elector.release()

    def stop(self, *_args) -> None:
        self._stop.set()
        for srv in self._servers:
            srv.shutdown()

    def install_signal_handlers(self) -> None:
        signal.signal(signal.SIGINT, self.stop)
        signal.signal(signal.SIGTERM, self.stop)


def main() -> int:
    import os

    # pin the JAX platform from env BEFORE anything dispatches: without
    # this, KARPENTER_TPU_PLATFORM/KARPENTER_TPU_FORCE_CPU are silently
    # ignored (the site bootstrap pins jax_platforms via jax.config,
    # which beats env vars) and the first solve initializes whatever
    # backend the site chose — hanging boot if the device is wedged
    from karpenter_tpu.utils.platform import configure
    configure()

    # HA deployment plumbing (deploy/: 2 replicas, one store daemon, one
    # shared lease — charts/karpenter/values.yaml:35's layout):
    #   KARPENTER_TPU_STORE_SOCKET  unix socket of a StoreDaemon; this
    #                               replica's cluster becomes an informer
    #                               cache over it (docs/store-backends.md)
    #   KARPENTER_TPU_LEASE_FILE    shared file lease → leader election
    #   KARPENTER_TPU_REPLICA_ID    identity in the lease (default pid)
    env = None
    store_sock = os.environ.get("KARPENTER_TPU_STORE_SOCKET")
    if store_sock:
        from karpenter_tpu.env import Environment
        from karpenter_tpu.store import RemoteBackend
        from karpenter_tpu.utils.clock import RealClock
        env = Environment(clock=RealClock(), options=Options.from_env(),
                          store_backend=RemoteBackend(store_sock))
    lease = None
    identity = None
    lease_file = os.environ.get("KARPENTER_TPU_LEASE_FILE")
    if lease_file:
        from karpenter_tpu.operator.leaderelection import FileLease
        lease = FileLease(lease_file)
        identity = os.environ.get(
            "KARPENTER_TPU_REPLICA_ID", f"replica-{os.getpid()}")
    op = Operator(
        metrics_port=int(os.environ.get("KARPENTER_TPU_METRICS_PORT", 8000)),
        health_port=int(os.environ.get("KARPENTER_TPU_HEALTH_PORT", 8081)),
        env=env, lease=lease, identity=identity)
    op.install_signal_handlers()
    op.serve()  # bind before the banner so the printed ports are real
    print(f"karpenter-tpu operator: cluster={op.options.cluster_name} "
          f"metrics=:{op.metrics_port} health=:{op.health_port}"
          + (f" replica={identity}" if identity else ""),
          flush=True)
    op.run()
    return 0
