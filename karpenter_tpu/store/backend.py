"""The store-backend seam (see package docstring).

Contract, mirroring the slice of `client.Client` + informers the
reference's controllers actually use
(/root/reference/cmd/controller/main.go:46-54):

- `load(kind)` — authoritative name→object snapshot (relist/recovery).
- `put(kind, name, obj)` — upsert the authoritative copy; returns False
  when the store rejected the write as a conflict (create of an existing
  name, modify of a deleted one — the apiserver-409 analogue). Called by
  the cluster AFTER the local cache mutation; the object may be the same
  mutable instance the cache holds, so implementations must serialize
  (or copy) before returning.
- `delete(kind, name)` — remove the authoritative copy.
- `events()` — drain peer mutations as (kind, verb, name, obj) tuples;
  obj is None for deletes. Self-originated echoes must NOT be returned
  (the local cache is already newer).
- `close()` — release resources.

Verbs are the cluster's watch verbs: added/modified/deleting/deleted.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class StoreBackend:
    def load(self, kind: str) -> Dict[str, object]:
        raise NotImplementedError

    def put(self, kind: str, name: str, obj: object,
            verb: str = "modified") -> bool:
        raise NotImplementedError

    def delete(self, kind: str, name: str) -> None:
        raise NotImplementedError

    def events(self) -> List[Tuple[str, str, str, Optional[object]]]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class InMemoryBackend(StoreBackend):
    """The default: the informer cache is the authority; every method is
    a no-op. Kept trivial on purpose — the in-process hot paths (50k-pod
    provisioning reconciles) must not pay a serialization tax for a seam
    they don't use."""

    def load(self, kind: str) -> Dict[str, object]:
        return {}

    def put(self, kind: str, name: str, obj: object,
            verb: str = "modified") -> bool:
        return True

    def delete(self, kind: str, name: str) -> None:
        pass

    def events(self) -> List[Tuple[str, str, str, Optional[object]]]:
        return []

    def close(self) -> None:
        pass
