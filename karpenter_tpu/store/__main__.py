"""`python -m karpenter_tpu.store <socket-path>` — run a standalone store
daemon (the deploy/ manifests' apiserver-analogue service; see
docs/store-backends.md)."""

import signal
import sys
import threading

from karpenter_tpu.store import StoreDaemon


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/karpenter-store.sock"
    daemon = StoreDaemon(path)
    print(f"karpenter-tpu store daemon: {path}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    daemon.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
