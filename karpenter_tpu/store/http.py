"""A kube-apiserver-protocol store backend (VERDICT r4 #6).

The third `StoreBackend`: REST list/watch JSON over chunked HTTP against
a minimal in-repo fake apiserver — the operating mode of the reference's
controllers (informers + `client.Client`,
/root/reference/cmd/controller/main.go:46-54) reduced to the slice this
framework's `Cluster` actually consumes.

Protocol (kube-shaped, per resource kind):

  GET    /apis/karpenter.tpu/v1/{kind}             list
      → {"kind": "...List", "metadata": {"resourceVersion": "N"},
         "items": [item, ...]}
  GET    /apis/karpenter.tpu/v1/{kind}?watch=true&resourceVersion=N
      → Transfer-Encoding: chunked; one JSON watch event per line:
        {"type": "ADDED|MODIFIED|DELETED", "object": item}
        410 Gone when N predates the retained event log (client relists
        and resumes — the informer ListAndWatch loop).
  POST   /apis/karpenter.tpu/v1/{kind}             create (409 if exists)
  PUT    /apis/karpenter.tpu/v1/{kind}/{name}      update (404 if absent)
  DELETE /apis/karpenter.tpu/v1/{kind}/{name}      delete (404 if absent)

Items are kube-shaped JSON envelopes:

  {"apiVersion": "karpenter.tpu/v1", "kind": "<Kind>",
   "metadata": {"name": ..., "resourceVersion": "17",
                "deletionTimestamp": ...?},
   "data": "<codec payload>"}

resourceVersion is a global monotonic counter (the etcd-revision
analogue); deletion-in-progress rides metadata.deletionTimestamp exactly
as in kube (a MODIFIED event whose object carries a deletionTimestamp is
the "deleting" verb).  Write responses return the stored item — the
client uses the returned resourceVersion to suppress its own watch
echoes, the same dedup a kube informer performs by revision.

The object payload codec is a seam: `PickleCodec` (default) base64s the
in-repo model objects; a real-cluster attach swaps it for the CRD JSON
codec plus auth/TLS plumbing — the protocol layer above does not change
(docs/store-backends.md).
"""

from __future__ import annotations

import base64
import http.client
import json
import pickle
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

GROUP_PATH = "/apis/karpenter.tpu/v1"


class PickleCodec:
    """Default payload codec: model objects ↔ base64 pickle.  Safe the
    same way the solverd/store-daemon pickles are: the fake apiserver is
    a loopback listener owned by the test/operator process, not an open
    network service.  The real-cluster codec (CRD JSON) replaces this
    without touching the protocol layer."""

    def encode(self, obj: object) -> str:
        return base64.b64encode(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)).decode()

    def decode(self, data: str) -> object:
        return pickle.loads(base64.b64decode(data))


class FakeApiServer:
    """Minimal kube-protocol apiserver: list/watch/create/update/delete
    with global resourceVersions, a bounded event log, and chunked watch
    streams.  Payload-agnostic — it stores and replays item JSON without
    decoding the codec body, exactly as a real apiserver treats specs."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 retain_events: int = 4096):
        self._lock = threading.Condition()
        # kind → name → item dict (with metadata.resourceVersion)
        self._data: Dict[str, Dict[str, dict]] = {}
        self._rv = 0
        # (rv, kind, type, item) — bounded; watches older than the tail
        # get 410 Gone and must relist
        self._log: List[Tuple[int, str, str, dict]] = []
        self._retain = retain_events
        self._closed = False

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _status(self, code: int, reason: str):
                self._json(code, {"kind": "Status", "code": code,
                                  "reason": reason})

            def _parts(self):
                u = urlparse(self.path)
                if not u.path.startswith(GROUP_PATH + "/"):
                    return None, None, {}
                rest = u.path[len(GROUP_PATH) + 1:].strip("/").split("/")
                kind = rest[0] if rest and rest[0] else None
                name = rest[1] if len(rest) > 1 else None
                return kind, name, parse_qs(u.query)

            def do_GET(self):
                kind, name, q = self._parts()
                if kind is None:
                    return self._status(404, "NotFound")
                if q.get("watch", ["false"])[0] in ("true", "1"):
                    return server._serve_watch(
                        self, kind,
                        int(q.get("resourceVersion", ["0"])[0]))
                # snapshot under the lock, write the response outside it —
                # a slow reader must not stall every writer behind the
                # store lock (kt-lint lock-discipline)
                items = rv = None
                with server._lock:
                    if name is not None:
                        item = server._data.get(kind, {}).get(name)
                    else:
                        items = list(server._data.get(kind, {}).values())
                        rv = server._rv
                if name is not None:
                    if item is None:
                        return self._status(404, "NotFound")
                    return self._json(200, item)
                return self._json(200, {
                    "kind": kind.capitalize() + "List",
                    "apiVersion": "karpenter.tpu/v1",
                    "metadata": {"resourceVersion": str(rv)},
                    "items": items})

            def _read_body(self) -> Optional[dict]:
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    return json.loads(self.rfile.read(n))
                except ValueError:
                    self._status(400, "BadRequest")
                    return None

            def do_POST(self):
                kind, _, _ = self._parts()
                if kind is None:
                    return self._status(404, "NotFound")
                item = self._read_body()
                if item is None:
                    return  # _read_body already answered 400
                name = item.get("metadata", {}).get("name")
                if not name:
                    return self._status(422, "Invalid")
                with server._lock:
                    if name in server._data.setdefault(kind, {}):
                        stored = None
                    else:
                        stored = server._commit(kind, name, item, "ADDED")
                if stored is None:
                    return self._status(409, "AlreadyExists")
                return self._json(201, stored)

            def do_PUT(self):
                kind, name, _ = self._parts()
                if kind is None:
                    return self._status(404, "NotFound")
                if name is None:
                    return self._status(405, "MethodNotAllowed")
                item = self._read_body()
                if item is None:
                    return  # _read_body already answered 400
                with server._lock:
                    if name not in server._data.setdefault(kind, {}):
                        # modify-of-deleted: the apiserver-404 analogue
                        stored = None
                    else:
                        stored = server._commit(kind, name, item, "MODIFIED")
                if stored is None:
                    return self._status(404, "NotFound")
                return self._json(200, stored)

            def do_DELETE(self):
                kind, name, _ = self._parts()
                if kind is None or name is None:
                    return self._status(404, "NotFound")
                with server._lock:
                    item = server._data.get(kind, {}).pop(name, None)
                    if item is not None:
                        server._rv += 1
                        tomb = dict(item)
                        tomb["metadata"] = dict(
                            item["metadata"],
                            resourceVersion=str(server._rv))
                        server._append_event(kind, "DELETED", tomb)
                if item is None:
                    return self._status(404, "NotFound")
                return self._json(200, tomb)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="kt-fake-apiserver")
        self._thread.start()

    # -- storage (lock held by callers) -----------------------------------
    def _commit(self, kind: str, name: str, item: dict,
                etype: str) -> dict:
        self._rv += 1
        stored = dict(item)
        stored["metadata"] = dict(item.get("metadata", {}),
                                  name=name,
                                  resourceVersion=str(self._rv))
        self._data[kind][name] = stored
        self._append_event(kind, etype, stored)
        return stored

    def _append_event(self, kind: str, etype: str, item: dict) -> None:
        self._log.append((self._rv, kind, etype, item))
        if len(self._log) > self._retain:
            del self._log[: len(self._log) - self._retain]
        self._lock.notify_all()

    # -- watch -------------------------------------------------------------
    def _serve_watch(self, handler, kind: str, rv: int) -> None:
        with self._lock:
            # decide under the lock, answer outside it — the 410 write
            # must not ride the store lock (kt-lint lock-discipline)
            expired = bool(self._log) and 0 < rv < self._log[0][0] - 1
        if expired:
            # the requested horizon fell off the log: 410 Gone, the
            # client relists (informer ListAndWatch recovery)
            return handler._status(410, "Expired")
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def chunk(payload: dict) -> bool:
            line = (json.dumps(payload) + "\n").encode()
            try:
                handler.wfile.write(f"{len(line):x}\r\n".encode()
                                    + line + b"\r\n")
                handler.wfile.flush()
                return True
            except OSError:
                return False

        last = rv
        while not self._closed:
            batch = []
            with self._lock:
                if self._log and last < self._log[0][0] - 1:
                    # the stream fell behind the bounded log mid-watch:
                    # events were trimmed unseen. Close the stream — the
                    # client reconnects from its last rv, receives 410,
                    # and relists (silently skipping the gap would lose
                    # peer events forever)
                    return
                for erv, ekind, etype, item in self._log:
                    if erv > last and ekind == kind:
                        batch.append((erv, etype, item))
                if not batch:
                    self._lock.wait(timeout=0.5)
            for erv, etype, item in batch:
                if not chunk({"type": etype, "object": item}):
                    return
                last = erv

    def close(self) -> None:
        self._closed = True
        with self._lock:
            self._lock.notify_all()
        self._httpd.shutdown()
        self._httpd.server_close()


class HttpBackend:
    """`StoreBackend` over the kube list/watch protocol.

    One watcher thread per kind (started on the kind's first `load`, as
    an informer starts per-resource reflectors), merging decoded events
    into one queue.  Own-write echoes are suppressed by a client-stamped
    metadata write-id recorded BEFORE the request goes out (the watch can
    deliver the echo before the write response returns, so a
    response-derived marker would race); own deletes are suppressed by a
    pending-delete marker per (kind, name).  A 410 Gone relists and
    diffs against the last-known name set, synthesizing DELETED events
    for names that vanished inside the gap."""

    def __init__(self, base_url: str, codec: Optional[PickleCodec] = None):
        u = urlparse(base_url)
        self._host = u.hostname
        self._port = u.port or 80
        self._codec = codec or PickleCodec()
        self._lock = threading.Lock()
        self._events: List[Tuple[str, str, str, Optional[object]]] = []
        self._own_write_ids: set = set()
        self._own_order: List[str] = []
        # own-delete markers, checkout-style (the discipline PR 11 gave
        # RemoteBackend._call): registered under the small lock BEFORE
        # the RPC goes out, resolved after it returns — no lock is ever
        # held across the wire.  Value is the delete's resourceVersion
        # once known (0 while the RPC is in flight); _relist_after_gap
        # uses it to decide whether the DELETED echo is still ahead of
        # the relist resume horizon (keep the marker) or fell behind it
        # (drop it, or it would swallow a PEER's later delete).
        self._pending_deletes: Dict[Tuple[str, str], int] = {}
        # kind → names whose own put committed while a relist was in
        # flight for that kind; the relist diff must not synthesize a
        # delete for them (their create raced the list snapshot)
        self._relist_touched: Dict[str, set] = {}
        self._relist_rv: Dict[str, int] = {}
        self._watchers: Dict[str, threading.Thread] = {}
        self._known: Dict[str, set] = {}
        self._closed = False
        self._rpc_lock = threading.Lock()
        self._rpc_conn: Optional[http.client.HTTPConnection] = None

    # -- HTTP plumbing -----------------------------------------------------
    def _conn(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self._host, self._port, timeout=30)

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Tuple[int, dict]:
        # one persistent keep-alive connection for RPCs (the server is
        # HTTP/1.1): per-call connect/teardown would pay TCP setup on
        # every cluster mutation. Reconnect-once on a broken socket.
        # child_span: store I/O annotates whatever trace is in flight (a
        # provisioning pass applying claims) but never starts one of its
        # own — the watch thread's polling would flood the ring buffer
        from karpenter_tpu.utils import tracing
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        with tracing.child_span("store.http.request", method=method,
                                path=path) as _sp:
            for attempt in (0, 1):
                # check the keep-alive connection out of its one-slot
                # pool and run the round trip OUTSIDE the lock: holding
                # _rpc_lock across the wire call serialized every caller
                # behind one slow response (kt-lint lock-discipline). A
                # concurrent caller finding the slot empty pays a fresh
                # connection instead of waiting.
                with self._rpc_lock:
                    conn, self._rpc_conn = self._rpc_conn, None
                if conn is None:
                    conn = self._conn()
                try:
                    conn.request(method, path, body=payload,
                                 headers=headers)
                    resp = conn.getresponse()
                    data = resp.read()
                except (OSError, http.client.HTTPException):
                    try:
                        conn.close()
                    except OSError:
                        pass
                    if attempt:
                        raise
                    continue
                with self._rpc_lock:
                    if self._rpc_conn is None and not self._closed:
                        self._rpc_conn = conn  # back into the pool
                        conn = None
                if conn is not None:
                    conn.close()
                break
            if _sp is not None:
                _sp.attrs["status"] = resp.status
        try:
            doc = json.loads(data) if data else {}
        except ValueError:
            doc = {}
        return resp.status, doc

    def _item(self, kind: str, name: str, obj: object,
              write_id: str) -> dict:
        meta = {"name": name, "kt-write-id": write_id}
        if getattr(getattr(obj, "meta", None), "deleting", False):
            # deletion-in-progress rides metadata, as in kube
            meta["deletionTimestamp"] = "1970-01-01T00:00:00Z"
        return {"apiVersion": "karpenter.tpu/v1",
                "kind": kind.rstrip("s").capitalize(),
                "metadata": meta,
                "data": self._codec.encode(obj)}

    def _note_own(self, write_id: str) -> None:
        with self._lock:
            self._own_write_ids.add(write_id)
            self._own_order.append(write_id)
            if len(self._own_order) > 4096:
                self._own_write_ids.discard(self._own_order.pop(0))

    # -- StoreBackend ------------------------------------------------------
    def load(self, kind: str) -> Dict[str, object]:
        status, doc = self._request("GET", f"{GROUP_PATH}/{kind}")
        if status != 200:
            return {}
        out = {}
        for item in doc.get("items", []):
            name = item["metadata"]["name"]
            out[name] = self._codec.decode(item["data"])
        rv = int(doc.get("metadata", {}).get("resourceVersion", "0"))
        with self._lock:
            self._known[kind] = set(out)
            if kind not in self._watchers and not self._closed:
                t = threading.Thread(target=self._watch_loop,
                                     args=(kind, rv), daemon=True,
                                     name=f"kt-http-watch-{kind}")
                self._watchers[kind] = t
                t.start()
        return out

    def put(self, kind: str, name: str, obj: object,
            verb: str = "modified") -> bool:
        import uuid
        write_id = uuid.uuid4().hex
        # recorded BEFORE the request: the watch stream can deliver the
        # echo before the HTTP response returns
        self._note_own(write_id)
        item = self._item(kind, name, obj, write_id)
        # RPC outside any lock (checkout-style, kt-lint lock-discipline);
        # the commit below records the name in _relist_touched when a
        # 410 relist is concurrently in flight, which is what keeps the
        # relist's list-then-diff from synthesizing a spurious delete
        # for a live object whose create raced the list snapshot
        if verb == "added":
            status, doc = self._request(
                "POST", f"{GROUP_PATH}/{kind}", item)
            if status == 409:
                return False
        else:
            status, doc = self._request(
                "PUT", f"{GROUP_PATH}/{kind}/{name}", item)
            if status == 404:
                return False
        if status in (200, 201):
            with self._lock:
                self._known.setdefault(kind, set()).add(name)
                touched = self._relist_touched.get(kind)
                if touched is not None:
                    touched.add(name)
            return True
        return False

    def delete(self, kind: str, name: str) -> None:
        with self._lock:
            # a marker is only consumable when a watcher is running
            # for the kind; otherwise it would linger and swallow a
            # PEER's later delete of the same name.  0 = RPC in flight.
            marked = kind in self._watchers
            if marked:
                self._pending_deletes[(kind, name)] = 0
        try:
            status, doc = self._request(
                "DELETE", f"{GROUP_PATH}/{kind}/{name}")
        except Exception:
            with self._lock:
                # a marker for a write that never happened would
                # swallow a peer's later delete of the same name
                self._pending_deletes.pop((kind, name), None)
            raise
        with self._lock:
            if status == 200:
                self._known.get(kind, set()).discard(name)
                rv = int(doc.get("metadata", {})
                         .get("resourceVersion", "0") or 0)
                if marked and (kind, name) in self._pending_deletes:
                    if rv and rv <= self._relist_rv.get(kind, 0):
                        # a relist overtook this delete: the DELETED
                        # echo predates the resume horizon, so the
                        # watcher will never consume the marker
                        self._pending_deletes.pop((kind, name), None)
                    else:
                        self._pending_deletes[(kind, name)] = rv
            else:
                self._pending_deletes.pop((kind, name), None)

    def events(self) -> List[Tuple[str, str, str, Optional[object]]]:
        with self._lock:
            out = self._events
            self._events = []
        return out

    def close(self) -> None:
        self._closed = True
        with self._rpc_lock:
            if self._rpc_conn is not None:
                try:
                    self._rpc_conn.close()
                except OSError:
                    pass
                self._rpc_conn = None

    # -- watch loop --------------------------------------------------------
    def _emit(self, kind: str, verb: str, name: str,
              obj: Optional[object]) -> None:
        with self._lock:
            self._events.append((kind, verb, name, obj))
            known = self._known.setdefault(kind, set())
            if verb == "deleted":
                known.discard(name)
            else:
                known.add(name)

    def _watch_loop(self, kind: str, rv: int) -> None:
        import time
        while not self._closed:
            try:
                conn = self._conn()
                conn.request(
                    "GET",
                    f"{GROUP_PATH}/{kind}?watch=true&resourceVersion={rv}")
                resp = conn.getresponse()
                if resp.status == 410:
                    conn.close()
                    rv = self._relist_after_gap(kind)
                    continue
                if resp.status != 200:
                    # transient server trouble (5xx against a real
                    # apiserver is routine): back off and re-establish —
                    # a dead watcher would silently lose every future
                    # peer event for this kind
                    conn.close()
                    time.sleep(0.2)
                    continue
                while not self._closed:
                    line = resp.readline()
                    if not line:
                        break  # stream closed; reconnect from last rv
                    event = json.loads(line)
                    if event.get("type") == "ERROR":
                        break  # kube error Status object: reconnect
                    item = event["object"]
                    rv = int(item["metadata"]["resourceVersion"])
                    name = item["metadata"]["name"]
                    wid = item["metadata"].get("kt-write-id")
                    with self._lock:
                        own = wid is not None and wid in self._own_write_ids
                    if own and event["type"] != "DELETED":
                        continue
                    if event["type"] == "DELETED":
                        with self._lock:
                            if (kind, name) in self._pending_deletes:
                                self._pending_deletes.pop((kind, name))
                                continue
                        self._emit(kind, "deleted", name, None)
                        continue
                    obj = self._codec.decode(item["data"])
                    if event["type"] == "ADDED":
                        verb = "added"
                    elif item["metadata"].get("deletionTimestamp"):
                        verb = "deleting"
                    else:
                        verb = "modified"
                    self._emit(kind, verb, name, obj)
                conn.close()
            except Exception:  # noqa: BLE001 — the watcher must survive
                # anything (parse error on a truncated line, refused
                # connection, codec hiccup); it reconnects from the last
                # good rv rather than dying unrestartably
                if self._closed:
                    return
                time.sleep(0.05)

    def _relist_after_gap(self, kind: str) -> int:
        """410 Gone: the watch horizon fell off the server's event log.
        Relist, diff against last-known names (synthesizing deletes for
        names that vanished inside the gap), and resume from the list's
        resourceVersion — informer ListAndWatch recovery.

        Checkout-style against concurrent own writes (no lock is held
        across the list RPC): a _relist_touched window is opened under
        the small lock before the GET goes out, own puts that commit
        inside the window record their name there, and the diff skips
        those names — a create racing the list snapshot must not be
        synthesized into a delete (its ADDED echo would then be
        swallowed by write-id suppression, losing the object for good).
        Own-delete markers are reconciled by resourceVersion: a marker
        whose DELETED echo predates the list's resourceVersion (the
        resume horizon) is dropped — the watcher will never consume it,
        and a lingering marker would swallow a peer's later delete —
        while markers still in flight or ahead of the horizon are kept,
        and their names are excluded from the diff so an own delete is
        never double-reported through gap recovery."""
        with self._lock:
            # open the touched window before the list RPC is issued
            self._relist_touched[kind] = set()
        status, doc = self._request("GET", f"{GROUP_PATH}/{kind}")
        if status != 200:
            with self._lock:
                self._relist_touched.pop(kind, None)
            return 0
        list_rv = int(doc.get("metadata", {}).get("resourceVersion", "0"))
        now = {}
        for item in doc.get("items", []):
            now[item["metadata"]["name"]] = item
        with self._lock:
            before = set(self._known.get(kind, set()))
            touched = self._relist_touched.pop(kind, set())
            own_deleting = set()
            kept: Dict[Tuple[str, str], int] = {}
            for (k, n), drv in self._pending_deletes.items():
                if k != kind:
                    kept[(k, n)] = drv
                    continue
                own_deleting.add(n)
                if drv == 0 or drv > list_rv:
                    # RPC still in flight, or the DELETED echo is ahead
                    # of the resume horizon: the watcher will consume it
                    kept[(k, n)] = drv
                # else: the echo fell behind the horizon — unconsumable
            self._pending_deletes = kept
            self._relist_rv[kind] = list_rv
        for name in sorted(before - set(now)):
            if name in touched or name in own_deleting:
                continue
            self._emit(kind, "deleted", name, None)
        for name, item in now.items():
            if name in own_deleting:
                continue  # mid-own-delete: the snapshot is already stale
            wid = item["metadata"].get("kt-write-id")
            with self._lock:
                if wid is not None and wid in self._own_write_ids:
                    continue  # our own write: the cache is current
            obj = self._codec.decode(item["data"])
            verb = ("deleting"
                    if item["metadata"].get("deletionTimestamp")
                    else "modified")
            self._emit(kind, verb, name, obj)
        return list_rv
