"""Pluggable cluster-store backends.

The reference's controllers never own their state: they watch CRs through
controller-runtime's informer cache and write back to kube-apiserver
(/root/reference/cmd/controller/main.go:46-54 hands every controller one
`client.Client`; recovery is relist — SURVEY §5 checkpoint/resume). This
package gives our `Cluster` the same split: the in-process object dicts
become an INFORMER CACHE, and a `StoreBackend` decides where the
authoritative copies live.

Three backends:

- `InMemoryBackend` — the cache IS the store (the default; zero overhead,
  identical semantics to the pre-seam Cluster).
- `RemoteBackend` (`remote.py`) — a process-external store daemon spoken
  to over a unix socket with a watch stream, the solverd pattern applied
  to state. Writes forward to the daemon; peers' writes stream back and
  update the local cache.
- `HttpBackend` (`http.py`) — the kube list/watch REST protocol over
  chunked HTTP against `FakeApiServer`, a minimal in-repo apiserver:
  global resourceVersions, watch streams, 410-Gone relist recovery,
  deletionTimestamp semantics. A REAL kube-apiserver attaches here by
  swapping the payload codec for CRD JSON plus auth/TLS
  (docs/store-backends.md).
"""

from karpenter_tpu.store.backend import InMemoryBackend, StoreBackend
from karpenter_tpu.store.http import FakeApiServer, HttpBackend, PickleCodec
from karpenter_tpu.store.remote import RemoteBackend, StoreDaemon

__all__ = [
    "FakeApiServer",
    "HttpBackend",
    "InMemoryBackend",
    "PickleCodec",
    "RemoteBackend",
    "StoreBackend",
    "StoreDaemon",
]
