"""Process-external store backend over a unix socket.

The solverd pattern (native/solverd.cc: one daemon, length-prefixed
frames, many clients) applied to cluster state: `StoreDaemon` holds the
authoritative pickled copies and fans mutation events out to every
watcher; `RemoteBackend` is the client — it forwards writes, drains peer
events, and hydrates relists. Two operator replicas pointed at one
daemon see one cluster, which is the 2-replica active/passive layout the
reference deploys (charts/karpenter/values.yaml:35) reduced to this
environment.

Wire format: 4-byte big-endian length + pickle. Messages are dicts:
  {op: "hello", client: id}                      → {ok}
  {op: "list", kind, rid}                        → {items: {name: bytes}, rid}
  {op: "put", kind, name, data, verb, rid}       → {ok, rid}
  {op: "delete", kind, name, rid}                → {ok, rid}
  {op: "watch", client: id}                      → {ok} registration ack,
      then a stream of {op: "event", kind, verb, name, data|None, origin}

RPCs carry a client-assigned request id the daemon echoes (`rid`), so a
response can be paired with — and verified against — its request
without holding the RPC lock across the round trip (ISSUE 12: the
lock-order fix that retired the PR 2 grandfathered lock-discipline
findings here).  The watch registration is ACKED under the daemon's
watcher lock: once the constructor returns, every subsequent peer write
is guaranteed to reach this backend's event buffer — the
registration-vs-first-write race was the `test_peer_events_flow` flake.

Pickle is safe here the same way it is for solverd: the socket is a
file-permission-guarded unix socket owned by the operator deployment,
not a network listener.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.utils import faults

_LEN = struct.Struct(">I")

# RemoteBackend: idle RPC connections kept for reuse (per backend)
_IDLE_POOL_CAP = 4


def _send(sock: socket.socket, msg: dict) -> None:
    data = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(buf)


class StoreDaemon:
    """Authoritative store: kind → name → pickled object."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._data: Dict[str, Dict[str, bytes]] = {}
        self._watchers: List[Tuple[str, socket.socket]] = []
        if os.path.exists(path):
            os.unlink(path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(path)
        self._srv.listen(16)
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="store-daemon")
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        client = "?"

        def reply(payload: dict) -> None:
            # echo the client's request id so the response pairs with
            # (and is verified against) exactly one request
            rid = msg.get("rid")
            _send(conn, dict(payload, rid=rid) if rid is not None
                  else payload)

        try:
            while True:
                msg = _recv(conn)
                if msg is None:
                    return
                op = msg.get("op")
                if op == "hello":
                    client = msg.get("client", "?")
                    reply({"ok": True})
                elif op == "list":
                    with self._lock:
                        items = dict(self._data.get(msg["kind"], {}))
                    reply({"items": items})
                elif op == "put":
                    verb = msg.get("verb", "modified")
                    with self._lock:
                        kind_map = self._data.setdefault(msg["kind"], {})
                        if verb != "added" and msg["name"] not in kind_map:
                            # modify/deleting against a name the store no
                            # longer holds: a peer deleted it first. A bare
                            # upsert would RESURRECT the object cluster-wide
                            # (kube-apiserver rejects this with a conflict);
                            # the writer's cache converges on its next sync.
                            conflict = True
                        elif verb == "added" and msg["name"] in kind_map:
                            # create of a name a peer already created (the
                            # failover dual-writer window with colliding
                            # generated names): last-write-wins would
                            # silently destroy the peer's object and leak
                            # whatever cloud resource it tracked — reject,
                            # like an apiserver 409; the writer rolls back
                            # its cache and retries under a fresh name.
                            conflict = True
                        else:
                            conflict = False
                            kind_map[msg["name"]] = msg["data"]
                    if conflict:
                        reply({"ok": False, "conflict": True})
                    else:
                        self._broadcast(msg.get("origin", client), {
                            "op": "event", "kind": msg["kind"],
                            "verb": verb,
                            "name": msg["name"], "data": msg["data"]})
                        reply({"ok": True})
                elif op == "delete":
                    with self._lock:
                        self._data.get(msg["kind"], {}).pop(msg["name"], None)
                    self._broadcast(msg.get("origin", client), {
                        "op": "event", "kind": msg["kind"], "verb": "deleted",
                        "name": msg["name"], "data": None})
                    reply({"ok": True})
                elif op == "watch":
                    with self._lock:
                        self._watchers.append((msg.get("client", client),
                                               conn))
                        # ack UNDER the watcher lock: a concurrent
                        # broadcast either snapshotted before the append
                        # (event not for us) or blocks on the lock until
                        # the ack is on the wire — so registration is
                        # strictly ordered before every event this
                        # watcher will ever receive, and a constructor
                        # that saw the ack can never miss a peer write
                        # (the test_peer_events_flow flake)
                        _send(conn, {"ok": True})  # kt-lint: disable=lock-discipline
                    return  # connection now belongs to the broadcast side
                else:
                    reply({"error": f"unknown op {op!r}"})
        except OSError:
            return

    def _broadcast(self, origin: str, event: dict) -> None:
        event = dict(event, origin=origin)
        with self._lock:
            watchers = list(self._watchers)
        dead = []
        for client, sock in watchers:
            if client == origin:
                continue  # echo suppression: the writer's cache is newer
            try:
                _send(sock, event)
            except OSError:
                dead.append((client, sock))
        if dead:
            with self._lock:
                self._watchers = [w for w in self._watchers if w not in dead]

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        # tear down the watch streams too: a watcher blocked in recv
        # must see EOF and mark its stream dead, or every replica's
        # wait_events() sleeps out its timeout against a daemon that
        # will never broadcast again
        with self._lock:
            watchers, self._watchers = list(self._watchers), []
        for _client, sock in watchers:
            try:
                sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class RemoteBackend:
    """Client half: synchronous RPCs over one connection, a watch stream
    on a second, peer events buffered for the cluster to drain on its
    reconcile cadence (informer semantics: level-driven, resync-safe)."""

    def __init__(self, path: str, timeout: float = 30.0):
        self.client_id = uuid.uuid4().hex
        self._path = path
        self._timeout = timeout
        self._closed = False
        self._rpc_lock = threading.Lock()
        self._rid = 0
        # small idle-connection pool (bounded): overlapping _call()s
        # each check out (or mint) their own socket, and up to
        # _IDLE_POOL_CAP come back for reuse — one slot would pay a
        # connect+hello handshake per overlapping RPC
        self._idle: List[socket.socket] = [self._rpc_connect()]
        self._watch_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._watch_sock.settimeout(timeout)
        self._watch_sock.connect(self._path)
        _send(self._watch_sock, {"op": "watch", "client": self.client_id})
        # registration ack (bounded by the connect timeout): once this
        # returns, the daemon has the watcher registered, so no peer
        # write after this constructor can be missed
        ack = _recv(self._watch_sock)
        if not (isinstance(ack, dict) and ack.get("ok")):
            self._watch_sock.close()
            raise ConnectionError(
                f"store daemon rejected watch registration: {ack!r}")
        # the watch STREAM blocks indefinitely by design: events arrive
        # whenever peers write, and close() unblocks the reader — an idle
        # timeout here would tear down a healthy quiet stream
        self._watch_sock.settimeout(None)  # kt-lint: disable=socket-discipline
        self._events: List[Tuple[str, str, str, Optional[object]]] = []
        self._events_cv = threading.Condition()
        self._watch_dead = False
        self._reader = threading.Thread(target=self._watch_loop, daemon=True,
                                        name="store-watch")
        self._reader.start()

    def _watch_loop(self) -> None:
        try:
            while not self._closed:
                try:
                    msg = _recv(self._watch_sock)
                except OSError:
                    return
                if msg is None:
                    return
                obj = (pickle.loads(msg["data"])
                       if msg.get("data") is not None else None)
                with self._events_cv:
                    self._events.append(
                        (msg["kind"], msg["verb"], msg["name"], obj))
                    self._events_cv.notify_all()
        finally:
            # stream death must wake wait_events() callers — and the
            # flag (not just the notify) is what makes a LATER waiter
            # fail fast instead of sleeping out its timeout against a
            # stream that will never deliver
            with self._events_cv:
                self._watch_dead = True
                self._events_cv.notify_all()

    def _rpc_connect(self) -> socket.socket:
        # every RPC is bounded: a wedged store daemon demotes this
        # replica (the caller sees the error and retries/records)
        # instead of freezing its reconcile loop forever behind one recv
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self._timeout)
        try:
            s.connect(self._path)
            _send(s, {"op": "hello", "client": self.client_id})
            _recv(s)
        except OSError:
            s.close()
            raise
        return s

    def _call(self, msg: dict) -> dict:
        """One bounded RPC round trip.  The cached connection is CHECKED
        OUT under `_rpc_lock` (set to None while in use) and the wire
        I/O runs outside the lock — each in-flight call owns a private
        socket, so request/response pairing holds per-socket and a
        wedged daemon stalls only the caller, never every thread queued
        behind the lock (the PR 2 grandfathered lock-discipline pair,
        now fixed).  The daemon echoes the request id; a mismatched
        echo means the connection desynchronized (a stale response from
        a timed-out predecessor) and the connection dies with it."""
        try:
            faults.fire("store.remote.rpc")
        except faults.FaultInjected as e:
            # translate to the store's native failure type so callers'
            # existing outage handling (retry next pass, record event)
            # is what the fault exercises
            raise ConnectionError(str(e)) from e
        with self._rpc_lock:
            sock = self._idle.pop() if self._idle else None
            self._rid += 1
            rid = self._rid
        try:
            if sock is None:
                sock = self._rpc_connect()
            _send(sock, dict(msg, origin=self.client_id, rid=rid))
            out = _recv(sock)
        except OSError as e:
            # includes a failed RECONNECT: callers' outage handling is
            # keyed on ConnectionError, never raw OSError subtypes.  A
            # timeout or partial read leaves response bytes in flight —
            # the connection dies with the failure; the next _call
            # reconnects fresh.
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise ConnectionError(f"store rpc failed: {e}") from e
        if out is None:
            sock.close()
            raise ConnectionError("store daemon closed the connection")
        if out.get("rid") != rid:
            sock.close()
            raise ConnectionError(
                f"store rpc desynchronized (sent rid {rid}, got "
                f"{out.get('rid')!r}) — dropping the connection")
        # return the connection to the idle pool (bounded; extras close)
        with self._rpc_lock:
            if not self._closed and len(self._idle) < _IDLE_POOL_CAP:
                self._idle.append(sock)
                sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        return out

    # -- StoreBackend interface -------------------------------------------
    def load(self, kind: str) -> Dict[str, object]:
        items = self._call({"op": "list", "kind": kind})["items"]
        return {name: pickle.loads(data) for name, data in items.items()}

    def put(self, kind: str, name: str, obj: object,
            verb: str = "modified") -> bool:
        # False = the daemon rejected the write as a conflict (create of
        # an existing name, modify of a peer-deleted one). Modify
        # conflicts are absorbable (the watch stream delivers the delete
        # and the cache converges); CREATE conflicts must bubble so the
        # writer can roll back its cache and pick a fresh name.
        out = self._call({"op": "put", "kind": kind, "name": name,
                          "verb": verb,
                          "data": pickle.dumps(
                              obj, protocol=pickle.HIGHEST_PROTOCOL)})
        return bool(out.get("ok", True))

    def delete(self, kind: str, name: str) -> None:
        self._call({"op": "delete", "kind": kind, "name": name})

    def events(self) -> List[Tuple[str, str, str, Optional[object]]]:
        with self._events_cv:
            out = self._events
            self._events = []
        return out

    def wait_events(self, count: int = 1, timeout: float = 5.0) -> bool:
        """Block until at least `count` events are buffered (without
        draining them) or `timeout` elapses.  Event-DRIVEN waiting for
        tests and consumers that would otherwise poll events() in a
        sleep loop; returns False on timeout or a dead watch stream."""
        import time
        deadline = time.monotonic() + timeout
        with self._events_cv:
            while len(self._events) < count:
                left = deadline - time.monotonic()
                if left <= 0 or self._closed or self._watch_dead:
                    return len(self._events) >= count
                self._events_cv.wait(left)
            return True

    def close(self) -> None:
        self._closed = True
        with self._rpc_lock:
            idle, self._idle = list(self._idle), []
        for s in idle + [self._watch_sock]:
            try:
                s.close()
            except OSError:
                pass
