"""Process-external store backend over a unix socket.

The solverd pattern (native/solverd.cc: one daemon, length-prefixed
frames, many clients) applied to cluster state: `StoreDaemon` holds the
authoritative pickled copies and fans mutation events out to every
watcher; `RemoteBackend` is the client — it forwards writes, drains peer
events, and hydrates relists. Two operator replicas pointed at one
daemon see one cluster, which is the 2-replica active/passive layout the
reference deploys (charts/karpenter/values.yaml:35) reduced to this
environment.

Wire format: 4-byte big-endian length + pickle. Messages are dicts:
  {op: "hello", client: id}                      → {ok}
  {op: "list", kind}                             → {items: {name: bytes}}
  {op: "put", kind, name, data, verb}            → {ok}
  {op: "delete", kind, name}                     → {ok}
  {op: "watch", client: id}                      → stream of
      {op: "event", kind, verb, name, data|None, origin}

Pickle is safe here the same way it is for solverd: the socket is a
file-permission-guarded unix socket owned by the operator deployment,
not a network listener.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.utils import faults

_LEN = struct.Struct(">I")


def _send(sock: socket.socket, msg: dict) -> None:
    data = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(buf)


class StoreDaemon:
    """Authoritative store: kind → name → pickled object."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._data: Dict[str, Dict[str, bytes]] = {}
        self._watchers: List[Tuple[str, socket.socket]] = []
        if os.path.exists(path):
            os.unlink(path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(path)
        self._srv.listen(16)
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="store-daemon")
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        client = "?"
        try:
            while True:
                msg = _recv(conn)
                if msg is None:
                    return
                op = msg.get("op")
                if op == "hello":
                    client = msg.get("client", "?")
                    _send(conn, {"ok": True})
                elif op == "list":
                    with self._lock:
                        items = dict(self._data.get(msg["kind"], {}))
                    _send(conn, {"items": items})
                elif op == "put":
                    verb = msg.get("verb", "modified")
                    with self._lock:
                        kind_map = self._data.setdefault(msg["kind"], {})
                        if verb != "added" and msg["name"] not in kind_map:
                            # modify/deleting against a name the store no
                            # longer holds: a peer deleted it first. A bare
                            # upsert would RESURRECT the object cluster-wide
                            # (kube-apiserver rejects this with a conflict);
                            # the writer's cache converges on its next sync.
                            conflict = True
                        elif verb == "added" and msg["name"] in kind_map:
                            # create of a name a peer already created (the
                            # failover dual-writer window with colliding
                            # generated names): last-write-wins would
                            # silently destroy the peer's object and leak
                            # whatever cloud resource it tracked — reject,
                            # like an apiserver 409; the writer rolls back
                            # its cache and retries under a fresh name.
                            conflict = True
                        else:
                            conflict = False
                            kind_map[msg["name"]] = msg["data"]
                    if conflict:
                        _send(conn, {"ok": False, "conflict": True})
                    else:
                        self._broadcast(msg.get("origin", client), {
                            "op": "event", "kind": msg["kind"],
                            "verb": verb,
                            "name": msg["name"], "data": msg["data"]})
                        _send(conn, {"ok": True})
                elif op == "delete":
                    with self._lock:
                        self._data.get(msg["kind"], {}).pop(msg["name"], None)
                    self._broadcast(msg.get("origin", client), {
                        "op": "event", "kind": msg["kind"], "verb": "deleted",
                        "name": msg["name"], "data": None})
                    _send(conn, {"ok": True})
                elif op == "watch":
                    with self._lock:
                        self._watchers.append((msg.get("client", client),
                                               conn))
                    return  # connection now belongs to the broadcast side
                else:
                    _send(conn, {"error": f"unknown op {op!r}"})
        except OSError:
            return

    def _broadcast(self, origin: str, event: dict) -> None:
        event = dict(event, origin=origin)
        with self._lock:
            watchers = list(self._watchers)
        dead = []
        for client, sock in watchers:
            if client == origin:
                continue  # echo suppression: the writer's cache is newer
            try:
                _send(sock, event)
            except OSError:
                dead.append((client, sock))
        if dead:
            with self._lock:
                self._watchers = [w for w in self._watchers if w not in dead]

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class RemoteBackend:
    """Client half: synchronous RPCs over one connection, a watch stream
    on a second, peer events buffered for the cluster to drain on its
    reconcile cadence (informer semantics: level-driven, resync-safe)."""

    def __init__(self, path: str, timeout: float = 30.0):
        self.client_id = uuid.uuid4().hex
        self._path = path
        self._timeout = timeout
        self._rpc_lock = threading.Lock()
        self._rpc: Optional[socket.socket] = self._rpc_connect()
        self._watch_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._watch_sock.settimeout(timeout)
        self._watch_sock.connect(self._path)
        _send(self._watch_sock, {"op": "watch", "client": self.client_id})
        # the watch STREAM blocks indefinitely by design: events arrive
        # whenever peers write, and close() unblocks the reader — an idle
        # timeout here would tear down a healthy quiet stream
        self._watch_sock.settimeout(None)  # kt-lint: disable=socket-discipline
        self._events: List[Tuple[str, str, str, Optional[object]]] = []
        self._events_lock = threading.Lock()
        self._closed = False
        self._reader = threading.Thread(target=self._watch_loop, daemon=True,
                                        name="store-watch")
        self._reader.start()

    def _watch_loop(self) -> None:
        while not self._closed:
            try:
                msg = _recv(self._watch_sock)
            except OSError:
                return
            if msg is None:
                return
            obj = (pickle.loads(msg["data"])
                   if msg.get("data") is not None else None)
            with self._events_lock:
                self._events.append(
                    (msg["kind"], msg["verb"], msg["name"], obj))

    def _rpc_connect(self) -> socket.socket:
        # every RPC is bounded: a wedged store daemon demotes this
        # replica (the caller sees the error and retries/records)
        # instead of freezing its reconcile loop forever behind one recv
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self._timeout)
        try:
            s.connect(self._path)
            _send(s, {"op": "hello", "client": self.client_id})
            _recv(s)
        except OSError:
            s.close()
            raise
        return s

    def _drop_rpc(self) -> None:
        # caller holds _rpc_lock. The protocol has no request ids: a
        # timeout or partial read leaves response bytes in flight, and
        # reusing the socket would pair the NEXT request with the
        # PREVIOUS response — the connection must die with the failure;
        # the next _call reconnects fresh
        if self._rpc is not None:
            try:
                self._rpc.close()
            except OSError:
                pass
            self._rpc = None

    def _call(self, msg: dict) -> dict:
        try:
            faults.fire("store.remote.rpc")
        except faults.FaultInjected as e:
            # translate to the store's native failure type so callers'
            # existing outage handling (retry next pass, record event)
            # is what the fault exercises
            raise ConnectionError(str(e)) from e
        with self._rpc_lock:
            try:
                if self._rpc is None:
                    self._rpc = self._rpc_connect()
                _send(self._rpc, dict(msg, origin=self.client_id))
                out = _recv(self._rpc)
            except OSError as e:
                # includes a failed RECONNECT: callers' outage handling
                # is keyed on ConnectionError, never raw OSError subtypes
                self._drop_rpc()
                raise ConnectionError(f"store rpc failed: {e}") from e
            if out is None:
                self._drop_rpc()
                raise ConnectionError("store daemon closed the connection")
        return out

    # -- StoreBackend interface -------------------------------------------
    def load(self, kind: str) -> Dict[str, object]:
        items = self._call({"op": "list", "kind": kind})["items"]
        return {name: pickle.loads(data) for name, data in items.items()}

    def put(self, kind: str, name: str, obj: object,
            verb: str = "modified") -> bool:
        # False = the daemon rejected the write as a conflict (create of
        # an existing name, modify of a peer-deleted one). Modify
        # conflicts are absorbable (the watch stream delivers the delete
        # and the cache converges); CREATE conflicts must bubble so the
        # writer can roll back its cache and pick a fresh name.
        out = self._call({"op": "put", "kind": kind, "name": name,
                          "verb": verb,
                          "data": pickle.dumps(
                              obj, protocol=pickle.HIGHEST_PROTOCOL)})
        return bool(out.get("ok", True))

    def delete(self, kind: str, name: str) -> None:
        self._call({"op": "delete", "kind": kind, "name": name})

    def events(self) -> List[Tuple[str, str, str, Optional[object]]]:
        with self._events_lock:
            out = self._events
            self._events = []
        return out

    def close(self) -> None:
        self._closed = True
        for s in (self._rpc, self._watch_sock):
            if s is None:
                continue  # the RPC socket may be down awaiting reconnect
            try:
                s.close()
            except OSError:
                pass
