"""Tenant-aware dispatch layer between the wire and the device (ISSUE 11
tentpole).

kt_solverd is a SHARED service for many clusters, not a per-cluster
sidecar (ROADMAP item 2).  The C++ batching window coalesces whatever
happens to arrive together; before this module, `handle_batch` fused
only same-fingerprint arrivals in window order, so under concurrent
multi-tenant load one heavy tenant could monopolize the single batcher
thread and incompatible arrivals serialized head-of-line.  This module
is the scheduler that sits between the parsed wire frames and the
device dispatch:

  * **Per-tenant bounded queues.**  Each tenant (the client-declared
    ``tenant`` field in the schedule frame body; default derived from
    the daemon connection id) gets its own queue, bounded at
    ``KARPENTER_TPU_TENANT_QUEUE`` requests.  Admission past the bound
    sheds the LOWEST-priority request — the incoming one, or a queued
    lower-priority one it evicts — counted on
    ``karpenter_tpu_service_tenant_shed_total{tenant,reason="admission"}``
    and answered with an explicit ``("shed", {...})`` response carrying
    the backpressure hint.  Never silent, never dropped.

  * **Weighted deficit-round-robin fairness.**  Each planning round
    credits every backlogged tenant ``quantum × weight`` deficit; a
    tenant spends one deficit per dispatched request.  Equal weights ⇒
    equal steady-state service; ``KARPENTER_TPU_TENANT_WEIGHTS``
    ("gold=4,free=1") buys a tenant a larger share.  A tenant's deficit
    resets when its queue empties (classic DRR — no hoarding credit
    while idle).

  * **Cross-tenant bucket fusion.**  Requests whose encoded problems
    land in the same padded bucket — key ``(catalog fingerprint,
    max_nodes, G bucket, E bucket)``, the exact jit-cache key the
    warmup lattice pre-traces — fuse into ONE vmapped ``solve_batch``
    device call even when they come from different tenants/clusters.
    The batch fills to ``max_fuse`` while matching demand and deficit
    last; fusing only WITHIN a bucket means a fused batch never drags
    its members to a bigger padded shape (no new compile cliffs).
    ``KARPENTER_TPU_TENANT_FUSE=off`` is the rollback knob: every
    request then dispatches alone, in the same DRR order.

  * **Deadline-aware dispatch order.**  The next batch normally seeds
    from the DRR rotation; when the oldest queued deadline is about to
    pass (within ~2× the dispatch-time EWMA), that request seeds the
    batch instead, so a deadline-pressed partial bucket dispatches
    early while full buckets otherwise fill.  A request whose deadline
    expires WHILE QUEUED is shed (reason="deadline"), counted, and
    answered — the daemon never burns the device for a caller that
    already gave up, and the caller gets a fast explicit answer instead
    of its timeout.

  * **Backpressure, not blind backoff.**  Every response (results and
    sheds alike) carries ``{queue_depth, eta_ms, retry_after_ms}`` —
    queue depth includes the C++ window backlog the daemon reported,
    and the ETA extrapolates from the dispatch EWMA and the observed
    fused-batch occupancy — so clients pace retries from the server's
    own estimate (service/resilience.py honors it).

Threading: the daemon calls `handle_batch` from its ONE batcher thread,
but in-process harnesses (tests/test_faults.py FakePySolverd,
service/loopback.py) may call it from several.  The scheduler is
therefore a real fan-in point: `pump()` elects one dispatcher at a time
(`_dispatch_fn_lock` — held across the device call by design, it IS the
device serialization), while `_lock` guards only queue state and is
NEVER held across a dispatch (kt-lint lock-discipline; the fixtures in
tests/test_lint.py encode exactly this split).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.solver.explain import SHED_ADMISSION, SHED_DEADLINE
from karpenter_tpu.utils import metrics

# per-tenant queue bound: past it, admission sheds lowest-priority first
DEFAULT_QUEUE_BOUND = 256
# DRR credit per backlogged tenant per planning round (requests)
DEFAULT_QUANTUM = 8
# fused-batch ceiling — mirrors the daemon's --max-batch and the
# kernel's B_BUCKETS[-1] chunk, so one fused dispatch is one device call
DEFAULT_MAX_FUSE = 64
# floor under the deadline-pressure window (seconds): even with a cold
# EWMA, a request within this margin of its deadline seeds the next batch
MIN_DEADLINE_SLACK = 0.25
# tenant-state cap: connection-derived tenants ("conn-<id>") are minted
# per accept, and a reconnecting undeclared client would otherwise grow
# queues/rotation/metric series forever — past this many tenants, idle
# empty queues are garbage-collected oldest-activity-first
TENANT_GC_CAP = 256
# keep a fused batch whole when its padding waste is small: a batch of
# n dispatches un-trimmed when n >= this fraction of the tier it would
# pad to (63 compatible requests ride ONE 64-padded call; 9 would waste
# 7/16 of a padded-16 call and ships as 4+4+1 instead)
PAD_KEEP_FRACTION = 0.75


def fuse_enabled() -> bool:
    """KARPENTER_TPU_TENANT_FUSE rollback knob (default on).  Re-read
    per planning round so in-process harnesses can flip it live."""
    from karpenter_tpu.utils.knobs import env_bool
    return env_bool("KARPENTER_TPU_TENANT_FUSE", default=True)


def parse_weights(spec: Optional[str]) -> Dict[str, float]:
    """"gold=4,free=1" → {"gold": 4.0, "free": 1.0}; malformed entries
    are ignored (a typo must not take the dispatch path down), weights
    clamp to a 0.1 floor so a mistyped 0 cannot starve a tenant."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            out[name.strip()] = max(0.1, float(val))
        except ValueError:
            continue
    return out


def load_weights(environ=None) -> Dict[str, float]:
    """Tenant weights from the CONFIG SURFACE (ROADMAP item 2 headroom):
    the optional weights file named by
    ``KARPENTER_TPU_TENANT_WEIGHTS_FILE`` — the operator-options /
    deploy-config surface (the supervisor's ``--tenant-weights-file``
    flag exports it to the worker) — overlaid by the
    ``KARPENTER_TPU_TENANT_WEIGHTS`` env knob, which STAYS the
    per-tenant override lever.  File grammar: the same ``tenant=weight``
    entries, one or many per line (commas or newlines), ``#`` comments;
    a missing or unreadable file degrades to the env knob alone, never
    crashes the daemon."""
    env = os.environ if environ is None else environ
    out: Dict[str, float] = {}
    path = env.get("KARPENTER_TPU_TENANT_WEIGHTS_FILE")
    if path:
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if line:
                        out.update(parse_weights(line))
        except (OSError, UnicodeDecodeError):
            # "unreadable degrades, never crashes the daemon" covers a
            # non-UTF-8 file (binary dropped by mistake) too — not an
            # OSError subclass
            pass
    out.update(parse_weights(env.get("KARPENTER_TPU_TENANT_WEIGHTS")))
    return out


class Item:
    """One queued schedule request.  `key` is the opaque fusion-bucket
    key (hashable; the backend builds it from the catalog fingerprint,
    max_nodes, and the padded G/E buckets), `payload` is whatever the
    backend needs to rebuild the request at dispatch time, and
    `respond` is the per-request answer callback — items from different
    `handle_batch` calls can ride one fused dispatch, so each item
    carries its own way home."""

    __slots__ = ("key", "tenant", "priority", "deadline", "payload",
                 "respond", "seq", "enqueued_at", "answered")

    def __init__(self, key, tenant: str, priority: int,
                 deadline: Optional[float], payload,
                 respond: Callable[[tuple], None], seq: int,
                 enqueued_at: float):
        self.key = key
        self.tenant = tenant
        self.priority = priority
        self.deadline = deadline
        self.payload = payload
        self.respond = respond
        self.seq = seq
        self.enqueued_at = enqueued_at
        self.answered = False


class _TenantQueue:
    """One tenant's bounded queue: items kept in (priority desc, arrival)
    order, plus the tenant's DRR ledger."""

    __slots__ = ("tenant", "weight", "deficit", "items",
                 "submitted", "dispatched", "shed", "last_active")

    def __init__(self, tenant: str, weight: float):
        self.tenant = tenant
        self.weight = weight
        self.deficit = 0.0
        self.items: List[Item] = []
        self.submitted = 0
        self.dispatched = 0
        self.shed: Dict[str, int] = {}
        self.last_active = 0.0

    def insert(self, item: Item) -> None:
        # total (priority desc, arrival seq) order; the scan-from-tail
        # keeps the common same-priority append fast, and makes
        # re-inserting a tier-trimmed item (lowest seq of its band) land
        # back at its original position
        i = len(self.items)
        key = (-item.priority, item.seq)
        while i > 0 and (-self.items[i - 1].priority,
                         self.items[i - 1].seq) > key:
            i -= 1
        self.items.insert(i, item)

    def lowest_priority(self) -> Optional[Item]:
        return self.items[-1] if self.items else None

    def pop_matching(self, key) -> Optional[Item]:
        """Next item (service order) whose bucket matches `key`; None
        when nothing in this queue fuses into the batch being built."""
        for i, item in enumerate(self.items):
            if key is None or item.key == key:
                return self.items.pop(i)
        return None


class TenantScheduler:
    def __init__(self, queue_bound: Optional[int] = None,
                 quantum: Optional[float] = None,
                 max_fuse: Optional[int] = None,
                 weights: Optional[Dict[str, float]] = None,
                 batch_tiers: Tuple[int, ...] = (4, 16, 64),
                 clock: Callable[[], float] = time.time):
        env = os.environ
        self.queue_bound = int(queue_bound if queue_bound is not None
                               else env.get("KARPENTER_TPU_TENANT_QUEUE",
                                            DEFAULT_QUEUE_BOUND))
        self.quantum = float(quantum if quantum is not None
                             else env.get("KARPENTER_TPU_TENANT_QUANTUM",
                                          DEFAULT_QUANTUM))
        self.max_fuse = int(max_fuse if max_fuse is not None
                            else env.get("KARPENTER_TPU_TENANT_MAX_FUSE",
                                         DEFAULT_MAX_FUSE))
        # demand-weighted batch sizing: the kernel's batch axis pads to
        # these tiers (solve.py B_BUCKETS), so a fused batch of 8 would
        # PAD to 16 and burn half the device call — trim each dispatch
        # down to the largest tier that fits the matching demand and
        # requeue the overflow (it front-runs the next batch, usually
        # fusing with fresh arrivals)
        self.batch_tiers = tuple(sorted(batch_tiers))
        self._weights = dict(weights) if weights is not None else \
            load_weights(env)
        self._clock = clock
        # _lock guards queue/ledger state only — never held across a
        # dispatch; _dispatch_fn_lock elects the single dispatcher and
        # IS held across the device call (that is the device
        # serialization, not a critical-section smell)
        self._lock = threading.Lock()
        self._dispatch_fn_lock = threading.Lock()
        self._done_cv = threading.Condition()
        self._queues: Dict[str, _TenantQueue] = {}
        self._rotation: List[str] = []
        self._cursor = 0
        self._seq = 0
        self._wire_backlog = 0
        # dispatch-time EWMA (seconds) + fused-occupancy EWMA: the ETA
        # model behind every backpressure hint
        self._ewma_s: Optional[float] = None
        self._occ_ewma: float = 1.0
        self._batches = 0
        self._cross_tenant_batches = 0
        self._fused_requests = 0

    # -- admission ---------------------------------------------------------
    def note_backlog(self, n: int) -> None:
        """The C++ window's queue depth behind the batch being handled —
        folded into queue_depth/ETA hints so clients see the whole line,
        not just the Python-side slice of it."""
        with self._lock:
            self._wire_backlog = max(0, int(n))

    def submit(self, *, key, tenant: str, priority: int = 0,
               deadline: Optional[float] = None, payload=None,
               respond: Callable[[tuple], None]) -> Item:
        """Admission-control one request into its tenant queue.  Always
        returns the Item; when admission shed it (queue full, lowest
        priority loses), the item is already answered with the explicit
        shed response and `pump` will skip it."""
        now = self._clock()
        with self._lock:
            tq = self._queue_for(tenant)
            tq.last_active = now
            self._seq += 1
            item = Item(key, tenant, int(priority), deadline, payload,
                        respond, self._seq, now)
            tq.submitted += 1
            victim = None
            if len(tq.items) >= self.queue_bound:
                lowest = tq.lowest_priority()
                if lowest is not None and lowest.priority < item.priority:
                    # evict the queued lower-priority request to admit
                    # the higher-priority arrival
                    victim = tq.items.pop()
                    tq.insert(item)
                else:
                    victim = item
            else:
                tq.insert(item)
            if victim is not item:
                # count only ADMITTED requests: this family is the
                # fairness denominator, and an over-driving tenant's
                # rejected flood must not inflate its apparent share
                metrics.SERVICE_TENANT_REQUESTS.inc(tenant=tenant)
            shed_resp = None
            if victim is not None:
                shed_resp = self._shed_locked(victim, SHED_ADMISSION)
            self._gc_tenants_locked()
            self._set_depth_gauges_locked()
        if victim is not None:
            self._answer(victim, shed_resp)
        return item

    def _gc_tenants_locked(self) -> None:
        """Bound tenant-state cardinality: connection-derived tenants
        are minted per accept, so a reconnecting undeclared client would
        otherwise grow queues, the rotation, and metric label series
        forever.  Past the cap, idle EMPTY queues go, oldest activity
        first; their gauge series is removed, and a conn-derived
        tenant's counter series too (it can never come back — the next
        connection gets a fresh id)."""
        if len(self._queues) <= TENANT_GC_CAP:
            return
        idle = sorted((tq for tq in self._queues.values()
                       if not tq.items),
                      key=lambda tq: tq.last_active)
        for tq in idle[:len(self._queues) - TENANT_GC_CAP]:
            del self._queues[tq.tenant]
            self._rotation.remove(tq.tenant)
            metrics.SERVICE_TENANT_QUEUE_DEPTH.remove(tenant=tq.tenant)
            if tq.tenant.startswith("conn-"):
                metrics.SERVICE_TENANT_REQUESTS.remove(tenant=tq.tenant)
                for reason in list(tq.shed):
                    metrics.SERVICE_TENANT_SHED.remove(
                        tenant=tq.tenant, reason=reason)
        if self._rotation:
            self._cursor %= len(self._rotation)
        else:
            self._cursor = 0

    def shed_inline(self, tenant: str, reason: str) -> tuple:
        """Build (and count) a shed response for a request the backend
        refuses before queueing — e.g. a frame whose deadline already
        passed at ingest.  Keeps ALL shed accounting in one place."""
        with self._lock:
            tq = self._queue_for(tenant)
            tq.shed[reason] = tq.shed.get(reason, 0) + 1
            metrics.SERVICE_TENANT_SHED.inc(tenant=tenant, reason=reason)
            return ("shed", self._hint_locked(reason=reason, tenant=tenant))

    # -- the pump ----------------------------------------------------------
    def pump(self, items: List[Item],
             dispatch: Callable[[object, List[Item]], List[tuple]]) -> None:
        """Block until every item in `items` is answered.  One caller at
        a time becomes the dispatcher (the device is serial anyway) and
        drains planned batches through `dispatch(key, batch)`, which
        must return one response tuple per batch item; other callers
        wait for their items to come back on someone else's batch."""
        mine = [it for it in items if not it.answered]
        while True:
            if all(it.answered for it in mine):
                return
            if self._dispatch_fn_lock.acquire(timeout=0.05):
                try:
                    self._drain(dispatch)
                finally:
                    self._dispatch_fn_lock.release()
                continue
            # another thread is dispatching (possibly carrying our
            # items in its fused batch): wait for answers, not the lock
            with self._done_cv:
                if not all(it.answered for it in mine):
                    self._done_cv.wait(0.05)

    def _drain(self, dispatch) -> None:
        """Dispatcher role: plan and execute batches until the queues
        are empty.  Caller holds `_dispatch_fn_lock`."""
        while True:
            with self._lock:
                plan = self._plan_locked(self._clock())
            if plan is None:
                return
            key, batch, sheds = plan
            for item, resp in sheds:
                self._answer(item, resp)
            if not batch:
                continue  # the round only shed expired items
            t0 = time.perf_counter()
            try:
                results = dispatch(key, batch)
            except Exception as e:  # noqa: BLE001 — answer, never wedge
                results = [("error", f"dispatch failed: {e}")] * len(batch)
            if len(results) != len(batch):
                results = list(results) + \
                    [("error", "dispatch returned a short result list")] * \
                    (len(batch) - len(results))
            self._note_dispatch(time.perf_counter() - t0, batch)
            for item, resp in zip(batch, results):
                self._answer(item, resp)

    # -- planning (all under self._lock) -----------------------------------
    def _plan_locked(self, now: float):
        """One weighted-DRR round → (key, batch, sheds) or None when
        every queue is empty.  Expired items are shed here — the
        while-queued half of the deadline contract."""
        sheds: List[Tuple[Item, tuple]] = []
        for tq in self._queues.values():
            kept = []
            for item in tq.items:
                if item.deadline is not None and now >= item.deadline:
                    sheds.append((item, self._shed_locked(item, SHED_DEADLINE)))
                else:
                    kept.append(item)
            tq.items = kept
        active = [tq for tq in self._queues.values() if tq.items]
        if not active:
            self._set_depth_gauges_locked()
            return None if not sheds else (None, [], sheds)
        # DRR credit: when every backlogged tenant has spent its credit,
        # start a new round — quantum × weight each, capped so an
        # idle-then-bursty tenant cannot hoard unbounded credit and lock
        # the device for a whole burst.  Crediting per ROUND (not per
        # batch) is what makes weights bite: a weight-3 tenant serves
        # three requests for every one of a weight-1 peer, not merely
        # alternating with it.
        cap = 4.0 * self.quantum
        if not any(tq.deficit >= 1.0 for tq in active):
            for tq in active:
                tq.deficit = min(tq.deficit + self.quantum * tq.weight,
                                 cap * max(tq.weight, 1.0))
        fuse = fuse_enabled()
        seed_tq = self._seed_tenant_locked(active, now)
        seed = seed_tq.pop_matching(None)
        seed_tq.deficit = max(0.0, seed_tq.deficit - 1.0)
        key = seed.key if fuse else None
        batch = [seed]
        if fuse:
            charged = len(active) > 1
            if not charged:
                # single backlogged tenant: fairness is moot, so the
                # deficit gate must not fragment its wide batch (a
                # 64-sim consolidation sweep rides ONE fused call, as
                # it did before the scheduler existed)
                while len(batch) < self.max_fuse:
                    item = seed_tq.pop_matching(key)
                    if item is None:
                        break
                    if item.deadline is not None and now >= item.deadline:
                        sheds.append(
                            (item, self._shed_locked(item, SHED_DEADLINE)))
                        continue
                    batch.append(item)
            else:
                order = self._rotation_from_locked(seed_tq.tenant)
                for tq in order:
                    while tq.deficit >= 1.0 and len(batch) < self.max_fuse:
                        item = tq.pop_matching(key)
                        if item is None:
                            break
                        if item.deadline is not None \
                                and now >= item.deadline:
                            sheds.append(
                                (item, self._shed_locked(item, SHED_DEADLINE)))
                            continue  # shedding is not service: no charge
                        batch.append(item)
                        tq.deficit -= 1.0
                    if len(batch) >= self.max_fuse:
                        break
            # demand-weighted batch sizing: keep the batch whole when
            # its padding waste is small (63 requests ride one
            # 64-padded call), otherwise trim to the largest exact tier
            # and requeue the overflow at its original (priority, seq)
            # position — a 9-item batch ships as 4 now + the rest next
            # round, usually fused with fresh arrivals
            n = len(batch)
            pad_tier = next((t for t in self.batch_tiers if t >= n),
                            self.batch_tiers[-1])
            if n > self.batch_tiers[0] and n < PAD_KEEP_FRACTION * pad_tier:
                allowed = max(t for t in self.batch_tiers if t <= n)
                for item in batch[allowed:]:
                    tq = self._queues[item.tenant]
                    tq.insert(item)
                    if charged:
                        tq.deficit += 1.0  # refund: it was never served
                batch = batch[:allowed]
        for tq in self._queues.values():
            if not tq.items:
                tq.deficit = 0.0  # classic DRR: empty queue keeps no credit
        for item in batch:
            self._queues[item.tenant].dispatched += 1
        self._set_depth_gauges_locked()
        return seed.key, batch, sheds

    def _seed_tenant_locked(self, active: List[_TenantQueue],
                            now: float) -> _TenantQueue:
        """Whose request seeds the next batch: normally the DRR seat —
        the rotation cursor STAYS on a tenant while it has credit and
        backlog, then advances, so service comes in weight-proportional
        runs rather than unweighted alternation.  A deadline about to
        pass (within ~2× the dispatch EWMA) preempts the rotation so
        the pressed request ships in a partial bucket instead of
        expiring behind full ones."""
        slack = max(MIN_DEADLINE_SLACK,
                    2.0 * (self._ewma_s if self._ewma_s else 0.0))
        pressed, pressed_dl = None, None
        for tq in active:
            for item in tq.items:
                if item.deadline is not None and \
                        item.deadline - now <= slack and \
                        (pressed_dl is None or item.deadline < pressed_dl):
                    pressed, pressed_dl = tq, item.deadline
        if pressed is not None:
            return pressed
        names = {tq.tenant for tq in active}
        for _ in range(len(self._rotation)):
            name = self._rotation[self._cursor % len(self._rotation)]
            if name in names and self._queues[name].deficit >= 1.0:
                return self._queues[name]
            self._cursor = (self._cursor + 1) % len(self._rotation)
        return active[0]

    def _rotation_from_locked(self, start: str) -> List[_TenantQueue]:
        names = self._rotation
        if start in names:
            i = names.index(start)
            ordered = names[i:] + names[:i]
        else:
            ordered = list(names)
        return [self._queues[n] for n in ordered if self._queues[n].items]

    def _queue_for(self, tenant: str) -> _TenantQueue:
        tq = self._queues.get(tenant)
        if tq is None:
            tq = _TenantQueue(tenant, self._weights.get(tenant, 1.0))
            self._queues[tenant] = tq
            self._rotation.append(tenant)
        return tq

    # -- accounting / hints ------------------------------------------------
    def _shed_locked(self, item: Item, reason: str) -> tuple:
        tq = self._queue_for(item.tenant)
        tq.shed[reason] = tq.shed.get(reason, 0) + 1
        metrics.SERVICE_TENANT_SHED.inc(tenant=item.tenant, reason=reason)
        return ("shed", self._hint_locked(reason=reason, tenant=item.tenant))

    def _answer(self, item: Item, resp: tuple) -> None:
        if item.answered:
            return
        try:
            item.respond(resp)
        except Exception:  # noqa: BLE001 — answering must never kill the pump
            pass
        item.answered = True
        with self._done_cv:
            self._done_cv.notify_all()

    def _note_dispatch(self, secs: float, batch: List[Item]) -> None:
        with self._lock:
            a = 0.3
            self._ewma_s = secs if self._ewma_s is None else \
                (1 - a) * self._ewma_s + a * secs
            self._occ_ewma = (1 - a) * self._occ_ewma + a * len(batch)
            self._batches += 1
            self._fused_requests += len(batch)
            cross = len({it.tenant for it in batch}) > 1
            if cross:
                self._cross_tenant_batches += 1
        metrics.SERVICE_FUSED_BATCHES.inc(
            cross_tenant="yes" if cross else "no")
        metrics.SERVICE_FUSED_BATCH_SIZE.observe(len(batch))

    def _depth_locked(self) -> int:
        return sum(len(tq.items) for tq in self._queues.values()) \
            + self._wire_backlog

    def _hint_locked(self, reason: Optional[str] = None,
                     tenant: Optional[str] = None) -> dict:
        depth = self._depth_locked()
        ewma_ms = (self._ewma_s or 0.0) * 1e3
        occ = max(self._occ_ewma, 1.0)
        # batches still ahead of a NEW arrival, each costing ~ewma
        eta_ms = round(ewma_ms * (depth / occ + 1.0), 3)
        hint = {"queue_depth": depth, "eta_ms": eta_ms,
                "retry_after_ms": eta_ms}
        if reason is not None:
            hint["reason"] = reason
        if tenant is not None:
            hint["tenant"] = tenant
        return hint

    def backpressure(self) -> dict:
        """The hint every successful response carries (the backend
        attaches it to each result): current depth incl. the wire
        backlog, and the EWMA-extrapolated ETA for a new arrival."""
        with self._lock:
            return self._hint_locked()

    def _set_depth_gauges_locked(self) -> None:
        for tq in self._queues.values():
            metrics.SERVICE_TENANT_QUEUE_DEPTH.set(
                len(tq.items), tenant=tq.tenant)

    def stats(self) -> dict:
        """Per-tenant + fleet dispatch state for the stats RPC and the
        dashboard merge (snapshot under the queue lock)."""
        with self._lock:
            total = sum(tq.dispatched for tq in self._queues.values())
            tenants = {
                tq.tenant: {
                    "queued": len(tq.items),
                    "weight": tq.weight,
                    "submitted": tq.submitted,
                    "dispatched": tq.dispatched,
                    "shed": dict(tq.shed),
                    "share": round(tq.dispatched / total, 4) if total else 0.0,
                } for tq in self._queues.values()}
            return {
                "fuse": fuse_enabled(),
                "tenants": tenants,
                "queue_depth": self._depth_locked(),
                "batches": self._batches,
                "cross_tenant_batches": self._cross_tenant_batches,
                "fused_requests": self._fused_requests,
                "occupancy_avg": round(
                    self._fused_requests / self._batches, 3)
                if self._batches else 0.0,
                "ewma_dispatch_ms": round((self._ewma_s or 0.0) * 1e3, 3),
            }
