"""Client for the kt_solverd solver service (native/solverd.cc).

Framing: u32 payload_len | u64 request_id | payload (both directions;
responses may arrive out of order). Payloads are pickled (kind, body)
tuples — see service/backend.py.

`SolverServiceClient` exposes the solver seam (`solve` / `solve_batch`)
so the control plane can point `GatedSolver` at a remote TPU-owning
process instead of the in-process solver. Catalogs are uploaded once per
content fingerprint (cached against the instance-type lists' identity,
the same invalidation signal TPUSolver uses) and referenced by hash
thereafter, keeping the steady-state request small: pods + cluster deltas
only. Concurrent requests coalesce in the daemon's native batch window
into one vmapped device call.

Resilience (ISSUE 7): every request runs under one shared
:class:`~karpenter_tpu.service.resilience.RetryPolicy` — bounded
attempts, exponential backoff + jitter, and a per-request deadline that
rides the wire frame (``body["deadline"]``, absolute epoch seconds —
unix-socket peers share a clock) so the daemon sheds work its caller
has already abandoned. A shared
:class:`~karpenter_tpu.service.resilience.CircuitBreaker` trips after
consecutive transport failures and fails fast while open, which is what
puts `GatedSolver` into explicit degraded mode (in-process solver, then
oracle) instead of paying a timeout per solve against a dead daemon.
Transport failures (connect/send/receive/timeout) raise
:class:`SolverServiceTransportError` and are retried; application
errors from a live daemon raise plain :class:`SolverServiceError` and
are not (the daemon answering is proof the transport works).

Multi-tenant (ISSUE 11): `tenant` and `priority` ride every schedule
frame; the daemon's fair scheduler (service/scheduler.py) queues each
tenant separately, sheds lowest-priority-first under pressure, and
fuses bucket-compatible requests ACROSS tenants into one device call.
A shed comes back as :class:`SolverServiceShed` — transport-class (so
fallbacks engage) but breaker-neutral (the daemon answering is proof of
life) — carrying the server's queue ETA, which `RetryPolicy.backoff`
uses as the retry pace instead of the blind exponential ladder.  The
latest backpressure hint is kept on `client.last_backpressure`.

Mesh: the daemon owns the devices, so its mesh story is configured in
ITS environment — `SOLVER_MESH` selects (backend._get_solver), and the
`KARPENTER_TPU_MESH=off/auto/N` rollback knob overrides inside the
daemon's solver exactly as in-process. `stats()` reports the resolved
mesh (device count + resident-path O-axis transfer counters) so a remote
operator can verify which story is live without shell access to the
daemon host.
"""

from __future__ import annotations

import hashlib
import pickle
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.scheduling.types import ScheduleInput, ScheduleResult
from karpenter_tpu.service.resilience import CircuitBreaker, RetryPolicy
from karpenter_tpu.utils import faults, metrics, tracing

# mirror of the daemon's kMaxFrame: a length prefix past this is frame
# desynchronization (a torn write, a corrupted header), not a real
# response — kill the connection instead of trying to allocate it
_MAX_FRAME = 256 << 20


class SolverServiceError(RuntimeError):
    """Base failure; also the daemon-reported application errors."""


class SolverServiceTransportError(SolverServiceError):
    """Connect/send/receive/timeout failures — the retryable class."""


class SolverServiceUnavailable(SolverServiceError):
    """Fail-fast signal while the circuit breaker is open."""


class SolverServiceShed(SolverServiceTransportError):
    """The daemon ANSWERED but refused the request — admission control
    (tenant queue full, lowest priority loses) or a deadline that passed
    at ingest/while queued (ISSUE 11).

    Transport-class so every existing fallback path (GatedSolver's
    degraded mode, the provisioner's re-batch-next-pass discipline)
    engages unchanged, but deliberately BREAKER-NEUTRAL: a daemon that
    sheds is alive and load-managing, not down, so `_with_retries`
    counts it a breaker success.  Carries the server's backpressure hint
    (`retry_after` seconds, plus the raw `backpressure` dict) so the
    retry pacing follows the daemon's own queue ETA instead of blind
    exponential backoff."""

    def __init__(self, msg: str, reason: str = "",
                 retry_after: Optional[float] = None,
                 backpressure: Optional[dict] = None):
        super().__init__(msg)
        self.reason = reason
        self.retry_after = retry_after
        self.backpressure = backpressure or {}

    @classmethod
    def from_body(cls, body) -> "SolverServiceShed":
        if not isinstance(body, dict):
            return cls(f"request shed by solver service: {body}")
        reason = str(body.get("reason", ""))
        ra = body.get("retry_after_ms")
        return cls(
            f"request shed by solver service (reason={reason or '?'}, "
            f"queue_depth={body.get('queue_depth')}, "
            f"eta_ms={body.get('eta_ms')})",
            reason=reason,
            retry_after=(float(ra) / 1e3) if ra else None,
            backpressure=dict(body))


class SolverServiceClient:
    def __init__(self, socket_path: str, timeout: float = 60.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 tenant: Optional[str] = None, priority: int = 0):
        self.socket_path = socket_path
        self.timeout = timeout
        # the retry policy's deadline defaults to the legacy `timeout`
        # knob so existing constructors keep their wait bound
        self.retry = retry or RetryPolicy(deadline=timeout)
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # multi-tenant identity (ISSUE 11): `tenant` rides every schedule
        # frame so the daemon's fair scheduler queues this control plane
        # under its own name (unset = the daemon derives a per-connection
        # tenant); `priority` is the admission-control rank — when a
        # tenant's queue is full the LOWEST priority is shed first
        self.tenant = tenant
        self.priority = int(priority)
        # last backpressure hint the daemon shipped (on a result or a
        # shed): {queue_depth, eta_ms, retry_after_ms} — callers can
        # inspect it to pace their own submission rate
        self.last_backpressure: Optional[dict] = None
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._next_id = 0
        self._pending: Dict[int, "threading.Event"] = {}
        self._responses: Dict[int, tuple] = {}
        self._reader: Optional[threading.Thread] = None
        # instance-type list identity → (fingerprint, payload). The strong
        # refs in _strong keep `id()`-keyed invalidation sound (a freed
        # list's address could be recycled — same discipline as TPUSolver)
        self._fingerprints: Dict[tuple, Tuple[str, bytes]] = {}
        self._strong: Dict[str, tuple] = {}
        self._uploaded: set = set()

    # -- connection -------------------------------------------------------
    def _ensure_connected(self) -> socket.socket:
        with self._lock:
            if self._sock is not None:
                return self._sock
        # connect OUTSIDE the lock: a wedged daemon would otherwise stall
        # every caller behind _lock for the full connect timeout (kt-lint
        # lock-discipline); losers of the install race close their socket
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        try:
            s.connect(self.socket_path)
        except OSError:
            s.close()
            raise
        with self._lock:
            if self._sock is not None:
                s.close()
                return self._sock
            self._sock = s
            # a fresh connection may face a restarted daemon with an empty
            # catalog store — re-upload on demand
            self._uploaded.clear()
            self._reader = threading.Thread(
                target=self._read_loop, args=(s,), daemon=True)
            self._reader.start()
            return s

    def close(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _read_loop(self, sock: socket.socket) -> None:
        """Reader-thread framing loop. The failure contract is absolute:
        HOWEVER this loop dies — clean EOF, mid-frame EOF, a timeout, an
        oversized length prefix, an injected fault, even an unexpected
        exception — the `finally` block fails every outstanding waiter
        fast. A waiter left to sleep out its full deadline against a
        dead connection is the bug this structure exists to prevent."""
        try:
            while True:
                faults.fire("service.client.recv")
                header = self._read_exact(sock, 12)
                if header is None:
                    break  # clean or mid-frame EOF: peer died
                plen, rid = struct.unpack("<IQ", header)
                if plen > _MAX_FRAME:
                    # frame desync/corruption: nothing after this point
                    # can be trusted — drop the connection
                    break
                payload = self._read_exact(sock, plen)
                if payload is None:
                    break
                try:
                    resp = pickle.loads(payload)
                except Exception as e:  # noqa: BLE001
                    resp = ("error", f"undecodable response: {e}")
                with self._lock:
                    ev = self._pending.get(rid)
                    if ev is not None:
                        # drop responses with no waiter (an abandoned rid
                        # after a client-side error/timeout) instead of
                        # accumulating them forever
                        self._responses[rid] = resp
                if ev is not None:
                    ev.set()
        except Exception:  # noqa: BLE001 — reader death is handled, not raised
            pass
        finally:
            # connection died: drop the socket so the next call
            # reconnects, and release every waiter
            with self._lock:
                if self._sock is sock:
                    self._sock = None
                for rid, ev in self._pending.items():
                    self._responses.setdefault(
                        rid, ("transport", "connection to solver service "
                                           "lost"))
                    ev.set()
            try:
                sock.close()
            except OSError:
                pass

    @staticmethod
    def _read_exact(sock, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- framing ----------------------------------------------------------
    def _send(self, kind: str, body: dict) -> int:
        try:
            sock = self._ensure_connected()
        except OSError as e:
            raise SolverServiceTransportError(
                f"solver service connect failed: {e}") from e
        payload = pickle.dumps((kind, body), protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._pending[rid] = threading.Event()
        frame = struct.pack("<IQ", len(payload), rid) + payload
        try:
            out = faults.fire("service.client.send", frame)
            with self._wlock:
                # holding the write lock across sendall is load-bearing:
                # frames from concurrent senders must not interleave on
                # the shared socket — responses are matched by request id,
                # so only the WRITE needs serializing, and this is it
                sock.sendall(out)  # kt-lint: disable=lock-discipline
            if len(out) != len(frame):
                # injected truncation: the daemon now waits mid-frame for
                # bytes that will never come — kill the connection so it
                # sees EOF (the torn-write failure shape end to end)
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                raise OSError("fault-injected frame truncation")
        except (OSError, faults.FaultInjected) as e:
            with self._lock:
                self._pending.pop(rid, None)
                if self._sock is sock:
                    self._sock = None
            raise SolverServiceTransportError(
                f"solver service send failed: {e}") from e
        return rid

    def _wait(self, rid: int, deadline: Optional[float] = None) -> tuple:
        """Block for rid's response until `deadline` (absolute epoch
        seconds; defaults to now + timeout). Reader death sets a
        ("transport", msg) marker, surfaced as the retryable class."""
        if deadline is None:
            deadline = time.time() + self.timeout
        with self._lock:
            ev = self._pending[rid]
        if not ev.wait(max(0.0, deadline - time.time())):
            with self._lock:
                self._pending.pop(rid, None)
                self._responses.pop(rid, None)
            raise SolverServiceTransportError("solver service timed out")
        with self._lock:
            self._pending.pop(rid, None)
            resp = self._responses.pop(rid)
        if not (isinstance(resp, tuple) and len(resp) == 2):
            # the daemon's internal-error marker (pickled None) or any
            # other malformed response
            raise SolverServiceError("solver service internal error")
        if resp[0] == "transport":
            raise SolverServiceTransportError(
                f"solver service: {resp[1]}")
        return resp

    # -- retry/breaker ----------------------------------------------------
    def _with_retries(self, fn: Callable[[float], object]):
        """Run `fn(deadline)` under the shared policy: breaker check up
        front (fail fast while open), bounded attempts with backoff on
        transport failures, everything inside ONE deadline. Application
        errors from a live daemon count as breaker successes — a daemon
        that answers is reachable, whatever it answered."""
        br = self.breaker
        if br is not None and not br.allow():
            raise SolverServiceUnavailable(
                "solver service circuit breaker open: failing fast")
        deadline = time.time() + self.retry.deadline
        attempt = 1
        while True:
            try:
                out = fn(deadline)
            except SolverServiceShed as e:
                # the daemon answered: it is ALIVE and load-shedding, so
                # the breaker records success (tripping it would demote
                # the control plane to degraded mode exactly when the
                # shared fleet is asking clients to pace themselves)
                if br is not None:
                    br.record_success()
                remaining = deadline - time.time()
                if e.reason == "deadline" or \
                        attempt >= self.retry.attempts or remaining <= 0:
                    # a deadline shed is not retryable — the budget this
                    # request rode in on has already passed
                    raise
                metrics.SERVICE_RETRIES.inc()
                # pace to the server's queue ETA, not the blind ladder
                time.sleep(min(self.retry.backoff(
                    attempt, retry_after=e.retry_after), remaining))
                attempt += 1
                continue
            except SolverServiceTransportError:
                if br is not None:
                    br.record_failure()
                remaining = deadline - time.time()
                if attempt >= self.retry.attempts or remaining <= 0:
                    raise
                if br is not None and not br.allow():
                    # our own failures tripped it mid-loop: stop burning
                    # the remaining attempts against a known-dead peer
                    raise SolverServiceUnavailable(
                        "solver service circuit breaker open: failing "
                        "fast") from None
                metrics.SERVICE_RETRIES.inc()
                time.sleep(min(self.retry.backoff(attempt), remaining))
                attempt += 1
                continue
            except SolverServiceError:
                if br is not None:
                    br.record_success()
                raise
            except BaseException:
                # anything unexpected (a malformed response body, a
                # KeyboardInterrupt mid-wait) must still RELEASE the
                # half-open probe slot, or the breaker wedges in
                # fail-fast forever; counting it as a failure is the
                # conservative release
                if br is not None:
                    br.record_failure()
                raise
            if br is not None:
                br.record_success()
            return out

    # -- catalog fingerprinting -------------------------------------------
    def _fingerprint(self, inp: ScheduleInput) -> Tuple[str, bytes]:
        pools = sorted(inp.nodepools, key=lambda p: (-p.weight, p.meta.name))
        lists = tuple(id(inp.instance_types.get(p.name)) for p in pools)
        # key mirrors TPUSolver._catalog_encoding: list identity AND pool
        # spec content (name/weight/static hash) — a pool edit that leaves
        # the type lists untouched must still re-upload
        key = (lists,
               tuple((p.meta.name, p.weight, p.static_hash()) for p in pools))
        cached = self._fingerprints.get(key)
        if cached is None:
            if len(self._fingerprints) >= 8:
                # superseded catalogs would otherwise pin multi-MB payloads
                # and dead instance-type lists forever
                self._fingerprints.clear()
                self._strong.clear()
            payload = pickle.dumps(
                {"nodepools": pools, "instance_types": inp.instance_types},
                protocol=pickle.HIGHEST_PROTOCOL)
            fp = hashlib.sha256(payload).hexdigest()
            cached = (fp, payload)
            self._fingerprints[key] = cached
            self._strong[fp] = tuple(inp.instance_types.values())
        return cached[0], cached[1]

    def _ensure_catalog(self, fp: str, payload: bytes,
                        deadline: Optional[float] = None) -> None:
        # connect FIRST: the upload ledger is per-connection state (a
        # reconnect clears it), so consulting it before the connection is
        # established reads a stale ledger from the previous daemon and
        # skips an upload the fresh daemon never saw — the need_catalog
        # retry in solve_batch then remains as the backstop for the
        # check-then-die race, not the primary path
        try:
            self._ensure_connected()
        except OSError as e:
            raise SolverServiceTransportError(
                f"solver service connect failed: {e}") from e
        if fp in self._uploaded:
            return
        body = pickle.loads(payload)
        rid = self._send("catalog", {
            "fingerprint": fp,
            "nodepools": body["nodepools"],
            "instance_types": body["instance_types"],
        })
        kind, _ = self._wait(rid, deadline)
        if kind != "ok":
            raise SolverServiceError(f"catalog upload failed: {kind}")
        self._uploaded.add(fp)

    def stats(self) -> dict:
        """Server-side batch/coalescing counters (observability + tests).
        Deliberately outside the breaker: diagnostics must keep working
        exactly when the breaker says the data path is unhealthy."""
        rid = self._send("stats", {})
        kind, body = self._wait(rid)
        if kind != "result":
            raise SolverServiceError(f"stats failed: {body}")
        return body

    def warmup(self, inp: ScheduleInput, shapes=(),
               batch_sizes=(1,)) -> int:
        """Remote padding-bucket precompile (solve.py TPUSolver.warmup):
        ships a representative input so the daemon pre-traces the kernel
        lattice before the first latency-sensitive schedule request.
        Returns the number of programs warmed."""
        fp, payload = self._fingerprint(inp)
        return self._with_retries(
            lambda deadline: self._warmup_once(
                inp, fp, payload, shapes, batch_sizes, deadline))

    def _warmup_once(self, inp: ScheduleInput, fp: str, payload: bytes,
                     shapes, batch_sizes, deadline: float,
                     _catalog_retry: bool = True) -> int:
        self._ensure_catalog(fp, payload, deadline)
        rid = self._send("warmup", {
            "fingerprint": fp,
            "pods": inp.pods,
            "existing_nodes": inp.existing_nodes,
            "daemon_overhead": inp.daemon_overhead,
            "remaining_limits": inp.remaining_limits,
            "shapes": tuple(shapes),
            "batch_sizes": tuple(batch_sizes),
            "deadline": deadline,
        })
        kind, body = self._wait(rid, deadline)
        if kind == "need_catalog":
            # restarted-empty daemon: same ledger-invalidation-and-replay
            # discipline as solve_batch (one retry, then raise)
            self._uploaded.clear()
            if not _catalog_retry:
                raise SolverServiceError(
                    "service lost the catalog again after re-upload")
            return self._warmup_once(inp, fp, payload, shapes, batch_sizes,
                                     deadline, _catalog_retry=False)
        if kind == "shed":
            self.last_backpressure = body if isinstance(body, dict) else None
            raise SolverServiceShed.from_body(body)
        if kind != "result":
            raise SolverServiceError(f"warmup failed: {body}")
        return int(body.get("warmed", 0))

    # -- the solver seam ---------------------------------------------------
    def solve(self, inp: ScheduleInput, max_nodes: Optional[int] = None,
              priority: Optional[int] = None) -> ScheduleResult:
        return self.solve_batch([inp], max_nodes=max_nodes,
                                priority=priority)[0]

    def solve_batch(self, inps: List[ScheduleInput],
                    max_nodes: Optional[int] = None,
                    priority: Optional[int] = None) -> List[ScheduleResult]:
        """`max_nodes` rides the schedule request so the disruption
        simulator's tiny-kernel cap survives the solverd deployment — the
        shared-TPU shape the cap matters most for.  `priority` overrides
        the client default for THIS call (a provisioning pass can outrank
        this tenant's own background consolidation sims).

        Shed handling is PARTIAL: results that arrived before/alongside
        a shed are kept, and the retry re-sends only the still-missing
        inputs — a 64-sim batch with one admission-shed member must not
        double the offered load exactly when the daemon asked for
        pacing.  (Schedule requests are stateless, so a transport-level
        retry re-solving a kept input would also be harmless — this is
        a load question, not a correctness one.)"""
        if not inps:
            return []
        done: Dict[int, ScheduleResult] = {}

        def once(deadline):
            todo = [i for i in range(len(inps)) if i not in done]
            partial: Dict[int, ScheduleResult] = {}
            try:
                got = self._solve_batch_once(
                    [inps[i] for i in todo], max_nodes, deadline,
                    priority=priority, partial=partial)
            except SolverServiceShed:
                for j, r in partial.items():
                    done[todo[j]] = r
                raise
            for j, r in enumerate(got):
                done[todo[j]] = r
            return [done[i] for i in range(len(inps))]

        with tracing.span("service.solve_batch", requests=len(inps)):
            return self._with_retries(once)

    @staticmethod
    def _groups_hint(inp: ScheduleInput) -> Optional[int]:
        """Pod-class count computed CLIENT-side so the daemon's single
        batcher thread doesn't pay a second O(pods) grouping pass per
        frame just to derive the fusion-bucket key (the solve re-groups
        authoritatively anyway; a wrong hint only costs fusion
        efficiency, never correctness)."""
        try:
            from karpenter_tpu.solver.encode import group_pods
            return len(group_pods(inp.pods))
        except Exception:  # noqa: BLE001 — hint only
            return None

    def _solve_batch_once(self, inps: List[ScheduleInput],
                          max_nodes: Optional[int],
                          deadline: float,
                          _catalog_retry: bool = True,
                          priority: Optional[int] = None,
                          partial: Optional[Dict[int, ScheduleResult]] = None
                          ) -> List[ScheduleResult]:
        fp, payload = self._fingerprint(inps[0])
        self._ensure_catalog(fp, payload, deadline)
        # the traceparent-style context field: the daemon extracts it, runs
        # the solve under the caller's trace, and ships its spans back on
        # the result so remote-solver phases stitch into this pass's trace
        tp = tracing.inject()
        rids = []
        for inp in inps:
            f, p = self._fingerprint(inp)
            self._ensure_catalog(f, p, deadline)
            body = {
                "fingerprint": f,
                "pods": inp.pods,
                "existing_nodes": inp.existing_nodes,
                "daemon_overhead": inp.daemon_overhead,
                "remaining_limits": inp.remaining_limits,
                "price_cap": inp.price_cap,
                "max_nodes": max_nodes,
                "traceparent": tp,
                # the daemon sheds a request whose caller's deadline has
                # already passed (peers share this host's clock)
                "deadline": deadline,
                # tenant/priority ride every frame so the daemon's fair
                # scheduler queues this cluster under its own identity
                "priority": self.priority if priority is None
                else int(priority),
                "groups_hint": self._groups_hint(inp),
            }
            if self.tenant is not None:
                body["tenant"] = self.tenant
            rids.append(self._send("schedule", body))
        results_pos: Dict[int, ScheduleResult] = {}
        shed_exc: Optional[SolverServiceShed] = None
        lost_catalog = False
        waited = 0
        try:
            for pos, rid in enumerate(rids):
                kind, body = self._wait(rid, deadline)
                waited = pos + 1
                if kind == "result":
                    remote_spans = getattr(body, "_remote_spans", None)
                    if remote_spans:
                        tracing.adopt(remote_spans)
                        try:
                            del body._remote_spans
                        except AttributeError:
                            pass
                    bp = getattr(body, "_backpressure", None)
                    if bp is not None:
                        # the daemon's queue estimate rides every result:
                        # keep the latest hint for retry pacing and for
                        # callers that adapt their own submission rate
                        self.last_backpressure = bp
                        try:
                            del body._backpressure
                        except AttributeError:
                            pass
                    results_pos[pos] = body
                elif kind == "need_catalog":
                    lost_catalog = True
                    break
                elif kind == "shed":
                    # keep DRAINING: the other frames were already sent
                    # and (mostly) answered — abandoning them would turn
                    # one shed into a whole-batch retry, doubling the
                    # offered load exactly when the daemon asked for
                    # pacing.  The first shed's hint is what we raise.
                    self.last_backpressure = body \
                        if isinstance(body, dict) else None
                    if shed_exc is None:
                        shed_exc = SolverServiceShed.from_body(body)
                else:
                    raise SolverServiceError(f"solver service error: {body}")
        finally:
            # on early exit, abandon the remaining rids so their pending
            # events and later-arriving responses don't accumulate forever
            if waited < len(rids):
                with self._lock:
                    for rid in rids[waited:]:
                        self._pending.pop(rid, None)
                        self._responses.pop(rid, None)
        if lost_catalog:
            # the daemon restarted empty: the upload ledger is stale — a
            # raise alone would leave it stale FOREVER (every later call
            # skips the upload, gets need_catalog again, and the control
            # plane stays demoted to the oracle). Invalidate and replay
            # once; schedule requests are stateless, so re-solving the
            # already-answered inputs is harmless.
            self._uploaded.clear()
            if not _catalog_retry:
                raise SolverServiceError(
                    "service lost the catalog again after re-upload")
            return self._solve_batch_once(inps, max_nodes, deadline,
                                          _catalog_retry=False,
                                          priority=priority,
                                          partial=partial)
        if shed_exc is not None:
            if partial is not None:
                partial.update(results_pos)
            raise shed_exc
        return [results_pos[i] for i in range(len(rids))]
