"""Shared retry/breaker policy for the solver-service client (ISSUE 7
tentpole part 2).

The seed client had one ad-hoc `_retry` flag (the need_catalog replay).
This module is the explicit availability story KubePACS-style systems
pair with a cost-optimal scheduler:

  * :class:`RetryPolicy` — bounded attempts, exponential backoff with
    jitter, and ONE per-request deadline that also rides the wire frame
    (`body["deadline"]`, absolute epoch seconds — unix-socket peers
    share a clock) so the daemon sheds work it cannot finish in time
    instead of solving for a caller that already gave up.
  * :class:`CircuitBreaker` — trips OPEN after N consecutive transport
    failures so a dead/wedged daemon costs one fast exception per solve
    (degraded mode in GatedSolver) instead of a full timeout each pass;
    after a cooldown, ONE half-open probe is let through — success
    closes the breaker, failure re-opens it for another cooldown.

State transitions are exported on
`karpenter_tpu_service_breaker_state` (0=closed, 1=open, 2=half-open);
the client counts retries on `karpenter_tpu_service_retries_total`.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from karpenter_tpu.utils import metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_VALUE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + jitter.

    `deadline` is the whole-request budget: attempts and their backoffs
    all fit inside it, and it is what the wire frame carries to the
    daemon. Jitter is a ±fraction of each backoff so a fleet of replicas
    retrying against one restarted daemon doesn't stampede in lockstep.
    """

    attempts: int = 3
    base_backoff: float = 0.05
    multiplier: float = 2.0
    max_backoff: float = 2.0
    jitter: float = 0.2
    deadline: float = 60.0

    def backoff(self, attempt: int,
                retry_after: "float | None" = None) -> float:
        """Sleep before retry number `attempt` (1-based).

        `retry_after` (seconds) is the server's backpressure hint — the
        queue-ETA the solverd scheduler ships on every shed response
        (ISSUE 11).  When present it REPLACES the exponential ladder:
        the server knows its own line length, so the client paces to
        that estimate (clamped to `max_backoff`, floored at
        `base_backoff` so a zero/cold hint cannot busy-spin) instead of
        blindly doubling.  Jitter still applies either way — a fleet of
        shed clients pacing to one shared ETA would otherwise stampede
        back in lockstep."""
        if retry_after is not None and retry_after > 0:
            raw = min(max(float(retry_after), self.base_backoff),
                      self.max_backoff)
        else:
            raw = min(self.base_backoff * (self.multiplier ** (attempt - 1)),
                      self.max_backoff)
        if self.jitter <= 0:
            return raw
        span = raw * self.jitter
        return max(0.0, raw + random.uniform(-span, span))


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe.

    Thread-safe; shared by every caller of one SolverServiceClient (the
    provisioner and the disruption simulator share the client, so they
    must share its view of the service's health).
    """

    def __init__(self, threshold: int = 5, cooldown: float = 10.0,
                 clock=time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        # the gauge is written on TRANSITIONS only, never here: an
        # operator process owns one solver service, but constructing a
        # second breaker (a re-built GatedSolver, a test) must not stomp
        # a live instance's open state back to "healthy". The gauge's
        # unset default (0) already reads as closed.

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str) -> None:
        # caller holds self._lock
        self._state = state
        metrics.SERVICE_BREAKER_STATE.set(_STATE_VALUE[state])

    def allow(self) -> bool:
        """May a request go out now? OPEN fails fast until the cooldown
        elapses, then exactly one caller becomes the half-open probe;
        everyone else keeps failing fast until the probe reports."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown:
                    return False
                self._set_state(HALF_OPEN)
                self._probing = True
                return True
            # HALF_OPEN: the probe slot is taken until it reports
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._set_state(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == HALF_OPEN:
                # the probe failed: re-open for another full cooldown
                self._opened_at = self._clock()
                self._set_state(OPEN)
            elif self._state == CLOSED and self._failures >= self.threshold:
                self._opened_at = self._clock()
                self._set_state(OPEN)
