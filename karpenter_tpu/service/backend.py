"""Server-side solver backend, executed inside kt_solverd's embedded
CPython interpreter (native/solverd.cc).

Requests are pickled tuples `(kind, body)`:

  ("catalog", {"fingerprint", "nodepools", "instance_types"})
      Upload + content-address a catalog. The cross-process analogue of
      the solver's device-resident catalog discipline (SURVEY §7 step 2:
      uploaded once per change, not per call): schedule requests then
      reference it by fingerprint, and because the server reuses the SAME
      list objects per fingerprint, TPUSolver's identity-keyed device
      cache holds across requests.
  ("schedule", {"fingerprint", "pods", "existing_nodes", "daemon_overhead",
                "remaining_limits", "price_cap", "tenant", "priority",
                "deadline"})
      One scheduling problem.  Schedule requests flow through the
      tenant-aware dispatcher (service/scheduler.py, ISSUE 11): bounded
      per-tenant queues with weighted deficit-round-robin fairness,
      priority-aware admission, and CROSS-TENANT fusion — requests whose
      encoded problems land in the same padded (G,E,N) bucket fuse into
      ONE vmapped device call even when they come from different
      clusters.  The per-(fingerprint,max_nodes) fusion that used to
      live inline here is now the inner stage of that scheduler.

Responses: ("result", ScheduleResult) | ("ok", None) |
           ("need_catalog", None) | ("error", message) |
           ("shed", {reason, tenant, queue_depth, eta_ms,
                     retry_after_ms})
The shed body doubles as the backpressure hint; successful results carry
the same hint as `result._backpressure` so clients pace retries from the
server's own queue estimate.
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.utils import faults

_catalogs: Dict[str, Tuple[list, dict]] = {}
_solver = None
# per-dispatch sizes of the schedule groups actually fused onto the
# device — exposed via the ("stats", _) request for tests/observability;
# bounded so a long-running daemon doesn't grow it forever.  Reset on
# worker init (reset_worker_state) and snapshotted under _state_lock:
# in-process harnesses restart the LOGICAL worker without restarting the
# process, and stats() must never report pre-restart history.
_batch_log: deque = deque(maxlen=1024)
# requests shed because their caller's deadline had already passed (at
# ingest or while queued in the tenant scheduler) or because admission
# control refused them — the daemon's half of the deadline/backpressure
# contract, reported via stats
_shed_count = 0
# the tenant-aware dispatcher (built lazily so stats-only callers never
# pay for it); guarded, like the counters above, by _state_lock
_scheduler = None
_state_lock = threading.Lock()


def reset_worker_state() -> None:
    """A fresh LOGICAL worker: clear the per-worker dispatch history
    (batch log, shed count, tenant queues/ledgers).  Called by the
    daemon right after importing this module (native/solverd.cc) and by
    in-process harnesses (service/loopback.py) on start, so a restarted
    worker never reports its predecessor's stats.  Uploaded catalogs
    survive deliberately — they are content-addressed and the
    need_catalog handshake re-validates them anyway."""
    global _shed_count, _scheduler
    with _state_lock:
        _batch_log.clear()
        _shed_count = 0
        _scheduler = None


def _get_scheduler():
    global _scheduler
    with _state_lock:
        if _scheduler is None:
            from karpenter_tpu.service.scheduler import TenantScheduler
            _scheduler = TenantScheduler()
        return _scheduler


def _get_solver():
    global _solver
    if _solver is None:
        import os
        # honors KARPENTER_TPU_PLATFORM / JAX_PLATFORMS /
        # KARPENTER_TPU_FORCE_CPU at the config level (site bootstraps pin
        # jax_platforms via jax.config, which beats the raw environment)
        from karpenter_tpu.utils.platform import configure
        configure()  # also enables the shared persistent compile cache
        from karpenter_tpu.solver import TPUSolver
        # SOLVER_MESH picks the daemon's mesh story the same way the
        # operator options do; KARPENTER_TPU_MESH (read inside the
        # solver per _resolve_mesh) stays the rollback override that
        # beats whatever was configured here
        # SOLVER_DELTA configures the daemon's delta-solve story the
        # same way; KARPENTER_TPU_DELTA stays the rollback override.
        # The delta cache lives server-side (records are device-adjacent
        # and far too heavy to ship per request): the daemon's own
        # value-based diff re-validates unpickled pods/nodes per pass.
        _solver = TPUSolver(
            max_nodes=int(os.environ.get("KARPENTER_TPU_MAX_NODES", "2048")),
            mesh=os.environ.get("SOLVER_MESH", "auto"),
            delta=os.environ.get("SOLVER_DELTA", "auto"))
    return _solver


def _solve_group(inps: List, max_nodes: Optional[int] = None) -> List:
    """Device batch with per-input fallback (never fail — SURVEY §5):
    first the whole fused batch, then per-input device/split solves, and
    only a truly unsupported input reaches the host oracle."""
    from karpenter_tpu.scheduling import Scheduler
    from karpenter_tpu.solver import UnsupportedPods
    try:
        # singleton groups stay on solve_batch: routing them through
        # solve() would compile the single-problem kernel shapes inside
        # the daemon on top of the batch shapes — an extra compile cliff
        # per deployment for no throughput win (phase observability rides
        # the batch path's own spans/histograms instead)
        return _get_solver().solve_batch(inps, max_nodes=max_nodes)
    except UnsupportedPods:
        out = []
        for inp in inps:
            try:
                out.append(_get_solver().solve(inp))
            except UnsupportedPods:
                out.append(Scheduler(inp).solve())
        return out


def _flight_record_batch(fp: str, inps: List, results: List,
                         max_nodes, tenants=()) -> None:
    """One flight record per fused solverd batch (the daemon's half of
    the request-record split): the catalog fingerprint the requests
    referenced, per-request pod counts, the tenants the fusion mixed,
    and a bit-exact digest per result — the solver's own per-attempt
    records carry the phase detail; this row ties a wire batch to them.
    Best-effort: the black box must never fail a batch."""
    try:
        from karpenter_tpu.utils import flightrecorder as fr
        from karpenter_tpu.utils import metrics, tracing
        rec = fr.RECORDER
        if not rec.enabled:
            return
        solver = _solver
        metrics.FLIGHT_RECORDS.inc(kind="batch")
        rec.record(
            kind="batch",
            trace_id=tracing.current_trace_id(),
            catalog={"fingerprint": fp},
            fingerprint=fp[:16] if isinstance(fp, str) else None,
            pods=sum(len(i.pods) for i in inps),
            groups=len(inps),
            knobs={"max_nodes": max_nodes,
                   "tenants": sorted(set(tenants))},
            phase_ms=dict(getattr(solver, "last_phase_ms", {}) or {})
            if solver is not None else {},
            delta=None,
            retraces=None,
            device_memory_peak_bytes=None,
            result={"requests": len(inps),
                    "digests": [fr.result_digest(r) for r in results]},
            capture=None,
        )
    except Exception:  # noqa: BLE001 — telemetry, never the data path
        pass


def _bucket_key(fp: str, max_nodes, body: dict) -> tuple:
    """The fusion-bucket key: requests fuse only when their PADDED device
    shapes match — same catalog fingerprint, same node-axis cap (a
    static kernel shape), same padded group-count and existing-node
    buckets.  This is exactly the jit-cache key the warmup lattice
    pre-traces, so a cross-tenant fused batch reuses warmed programs
    instead of opening new compile cliffs.  The group count normally
    arrives as the client-computed `groups_hint` (so this daemon's
    single batcher thread doesn't pay a second O(pods) grouping per
    frame; a wrong hint only costs fusion efficiency — the solve
    re-groups authoritatively); hintless frames run `group_pods` here,
    and anything unexpected degrades the key to per-fingerprint
    fusion — the pre-scheduler behavior — rather than failing the
    request."""
    try:
        from karpenter_tpu.solver.encode import bucket, group_pods
        from karpenter_tpu.solver.solve import E_BUCKETS, G_BUCKETS
        hint = body.get("groups_hint")
        n_groups = int(hint) if isinstance(hint, int) and hint > 0 \
            else len(group_pods(body["pods"]))
        g = bucket(max(n_groups, 1), G_BUCKETS)
        e = bucket(len(body.get("existing_nodes") or []), E_BUCKETS)
    except Exception:  # noqa: BLE001 — degrade, never refuse
        g = e = None
    return (fp, max_nodes, g, e)


def _tenant_of(body: dict, conn_ids, i: int) -> str:
    """Client-declared tenant, else a per-connection identity (each
    control-plane replica's connection is its own tenant by default)."""
    tenant = body.get("tenant")
    if tenant:
        return str(tenant)
    if conn_ids is not None and i < len(conn_ids):
        return f"conn-{conn_ids[i]}"
    return "default"


def _dispatch_fused(key, batch) -> List[tuple]:
    """The inner dispatch stage: one fused (fingerprint, max_nodes,
    bucket) group → one vmapped device call.  Runs OUTSIDE the
    scheduler's queue lock (only the dispatcher election serializes it).
    Returns one response tuple per batch item."""
    from karpenter_tpu.scheduling import ScheduleInput
    from karpenter_tpu.utils import tracing
    fp, max_nodes = key[0], key[1]
    with _state_lock:
        _batch_log.append(len(batch))
        cat = _catalogs.get(fp)
    if cat is None:
        # the catalog vanished between admission and dispatch (only
        # possible through an in-process reset): the handshake recovers
        return [("need_catalog", None)] * len(batch)
    nodepools, instance_types = cat
    inps = []
    for item in batch:
        _i, body = item.payload
        inps.append(ScheduleInput(
            pods=body["pods"],
            nodepools=nodepools,
            instance_types=instance_types,
            existing_nodes=body.get("existing_nodes") or [],
            daemon_overhead=body.get("daemon_overhead") or {},
            remaining_limits=body.get("remaining_limits") or {},
            price_cap=body.get("price_cap"),
        ))
    # stitch the fused solve into the CALLER's trace: extract the
    # first traceparent in the group (a fused batch normally comes
    # from one operator client), run the solve as its child, and ship
    # the recorded spans back on each matching response — the spans
    # belong to the caller's ring buffer, not this daemon's
    tp = next((item.payload[1].get("traceparent") for item in batch
               if item.payload[1].get("traceparent")), None)
    ctx = tracing.extract(tp)
    try:
        with ctx:
            with tracing.span("solverd.solve_batch", requests=len(batch),
                              tenants=len({it.tenant for it in batch})):
                results = _solve_group(inps, max_nodes=max_nodes)
        _flight_record_batch(fp, inps, results, max_nodes,
                             tenants=[it.tenant for it in batch])
        hint = _get_scheduler().backpressure()
        spans = [s.to_dict() for s in ctx.spans]
        out: List[tuple] = []
        for item, res in zip(batch, results):
            if spans and item.payload[1].get("traceparent") == tp:
                try:
                    # exactly ONE response carries the group's spans: a
                    # fused 60-sim batch attaching (and the client
                    # adopting) the same list per result would
                    # duplicate every span ~60x in the caller's trace
                    res._remote_spans = spans
                    spans = []
                except AttributeError:
                    pass  # a slotted result type: spans are best-effort
            try:
                # explicit backpressure: the client adapts its retry
                # pacing to the daemon's own queue estimate instead of
                # blind exponential backoff
                res._backpressure = dict(hint)
            except AttributeError:
                pass
            out.append(("result", res))
        return out
    except Exception as e:  # noqa: BLE001
        return [("error", f"solve failed: {e}")] * len(batch)


def handle_batch(payloads: List[bytes], conn_ids=None,
                 backlog: int = 0) -> List[bytes]:
    """One C++ window's worth of frames.  `conn_ids` (parallel to
    `payloads`) carries the daemon's per-connection identities for the
    default-tenant derivation; `backlog` is the window queue depth
    BEHIND this batch, folded into every backpressure hint.  Both are
    optional so in-process callers (tests, FakePySolverd) keep working
    with bare payload lists."""
    global _shed_count
    from karpenter_tpu.scheduling import ScheduleInput

    # fault-matrix hook (utils/faults.py): `crash` here is the
    # worker-killed-mid-batch scenario — the supervisor must restart the
    # process and clients must fail their in-flight requests fast
    faults.fire("solverd.handle_batch")

    n = len(payloads)
    responses: List[Optional[tuple]] = [None] * n
    requests: List[Optional[tuple]] = [None] * n
    for i, raw in enumerate(payloads):
        # one replica's malformed frame must never poison the coalesced
        # batch — validate shape per request, answer per request
        try:
            req = pickle.loads(raw)
            if not (isinstance(req, tuple) and len(req) == 2
                    and isinstance(req[1], dict)):
                raise ValueError("request must be a (kind, body-dict) tuple")
            requests[i] = req
        except Exception as e:  # noqa: BLE001
            responses[i] = ("error", f"unpicklable request: {e}")

    # catalog uploads first so same-batch schedule requests can use them
    for i, req in enumerate(requests):
        if req is None or responses[i] is not None:
            continue
        kind, body = req
        if kind == "catalog":
            try:
                _catalogs[body["fingerprint"]] = (
                    body["nodepools"], body["instance_types"])
                responses[i] = ("ok", None)
            except KeyError as e:
                responses[i] = ("error", f"catalog body missing {e}")
        elif kind == "stats":
            # mesh observability: remote operators (and the multichip
            # bench) see whether the daemon actually sharded, and how
            # much O-axis traffic the resident path has shipped
            mesh_info = None
            if _solver is not None and _solver._mesh_exec is not None:
                ex = _solver._mesh_exec
                mesh_info = {
                    "devices": _solver.mesh.size,
                    "o_axis_transfers": len(ex.transfers),
                    "o_axis_bytes": sum(b for _, b in ex.transfers),
                }
            # the worker's telemetry snapshot rides the stats RPC: this
            # is how the daemon's solve-rate, phase latencies, delta
            # split, retraces, and flight-recorder tail reach the
            # operator's GET /debug/dashboard without the daemon
            # exposing its own HTTP surface (utils/telemetry.py merges
            # it alongside the supervisor's and the operator's own).
            # The per-tenant scheduler section is how "one solver,
            # many clusters" stays operable: queue depth, fairness
            # share, shed and fusion counters per tenant.
            from karpenter_tpu.utils import telemetry
            with _state_lock:
                batch_sizes = list(_batch_log)
                shed = _shed_count
                sched = _scheduler
            responses[i] = ("result", {"batch_sizes": batch_sizes,
                                       "catalogs": len(_catalogs),
                                       "shed": shed,
                                       "mesh": mesh_info,
                                       "scheduler":
                                           sched.stats() if sched else None,
                                       "telemetry":
                                           telemetry.local_snapshot()})
        elif kind == "warmup":
            # padding-bucket precompile against an uploaded catalog: the
            # operator fires this at startup so the daemon's first real
            # schedule request meets a fully-compiled kernel lattice
            # (solve.py warmup; the persistent compile cache makes a
            # daemon RESTART skip even this step's XLA work)
            deadline = body.get("deadline")
            if deadline is not None and time.time() >= deadline:
                # the shed contract covers warmup FIRST of all: it is
                # the most expensive request kind, and a queued warmup
                # whose caller already gave up would hold the single
                # batcher thread through minutes of compile while real
                # schedule requests wait behind it
                with _state_lock:
                    _shed_count += 1
                responses[i] = _get_scheduler().shed_inline(
                    _tenant_of(body, conn_ids, i), "deadline")
                continue
            fp = body.get("fingerprint")
            if fp not in _catalogs:
                responses[i] = ("need_catalog", None)
                continue
            nodepools, instance_types = _catalogs[fp]
            try:
                inp = ScheduleInput(
                    pods=body.get("pods") or [],
                    nodepools=nodepools,
                    instance_types=instance_types,
                    existing_nodes=body.get("existing_nodes") or [],
                    daemon_overhead=body.get("daemon_overhead") or {},
                    remaining_limits=body.get("remaining_limits") or {},
                )
                warmed = _get_solver().warmup(
                    inp, shapes=tuple(body.get("shapes") or ()),
                    batch_sizes=tuple(body.get("batch_sizes") or (1,)))
                responses[i] = ("result", {"warmed": warmed})
            except Exception as e:  # noqa: BLE001
                responses[i] = ("error", f"warmup failed: {e}")

    # schedule requests flow through the tenant scheduler: bounded
    # per-tenant queues → weighted-DRR planning → cross-tenant
    # bucket-fused device dispatches (_dispatch_fused is the inner
    # stage the old inline (fingerprint, max_nodes) grouping became)
    sched = None
    items = []
    for i, req in enumerate(requests):
        if req is None or responses[i] is not None:
            continue
        kind, body = req
        if kind != "schedule":
            responses[i] = ("error", f"unknown request kind {kind!r}")
            continue
        fp = body.get("fingerprint")
        if "pods" not in body:
            responses[i] = ("error", "schedule body missing pods")
            continue
        tenant = _tenant_of(body, conn_ids, i)
        deadline = body.get("deadline")
        if deadline is not None and time.time() >= deadline:
            # the caller's deadline already passed (it timed out, fell
            # back, and will re-send the pods next pass): solving now
            # burns the device for a result nobody reads, and behind a
            # restart backlog it keeps the daemon permanently late —
            # shed instead (peers share this host's clock)
            with _state_lock:
                _shed_count += 1
            responses[i] = _get_scheduler().shed_inline(tenant, "deadline")
            continue
        if fp not in _catalogs:
            responses[i] = ("need_catalog", None)
            continue
        if sched is None:
            sched = _get_scheduler()
            sched.note_backlog(backlog)

        def _respond(resp, i=i):
            if resp[0] == "shed":
                global _shed_count
                with _state_lock:
                    _shed_count += 1
            responses[i] = resp

        items.append(sched.submit(
            key=_bucket_key(fp, body.get("max_nodes"), body),
            tenant=tenant,
            priority=int(body.get("priority") or 0),
            deadline=deadline,
            payload=(i, body),
            respond=_respond))
    if items:
        sched.pump(items, _dispatch_fused)

    return [pickle.dumps(r, protocol=pickle.HIGHEST_PROTOCOL)
            for r in responses]
