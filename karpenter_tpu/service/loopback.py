"""In-process kt_solverd stand-in: the real wire framing, the C++
batching-window semantics, and the real backend — no native toolchain.

`LoopbackSolverd` re-implements native/solverd.cc's runtime in plain
Python threads: a unix-socket listener, per-connection reader threads
feeding one bounded window queue, and a single batcher thread that
collects a window (first request opens it; it closes on an idle gap, the
max-window wall, or the max batch size) and hands the whole batch to
`backend.handle_batch(payloads, conn_ids, backlog)` — the same
three-argument seam the daemon uses, so the tenant scheduler's
per-connection default tenants and backpressure hints behave
identically.  `SolverServiceClient` connects to it unchanged.

This is the test/bench seam for the multi-tenant dispatch layer
(ISSUE 11): the saturation smoke (`make saturation-smoke`), the
scheduler's end-to-end tests, and `benchmarks/config8_saturation.py
--loopback` all drive real concurrent clients through a real window
without building the native binary.  It is NOT the deployment shape —
the C++ daemon owns the TPU process in production (docs/operations.md).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from collections import deque
from typing import Optional

_MAX_FRAME = 256 << 20  # mirror of the daemon's kMaxFrame


class _Window:
    """The C++ Batcher's queue + condition, in Python."""

    def __init__(self, idle_ms: float, max_ms: float, max_batch: int):
        self.idle_s = idle_ms / 1e3
        self.max_s = max_ms / 1e3
        self.max_batch = max_batch
        self.cv = threading.Condition()
        self.queue: deque = deque()  # (conn, conn_id, rid, payload)
        self.stopping = False

    def push(self, entry) -> None:
        with self.cv:
            self.queue.append(entry)
            self.cv.notify()

    def collect(self):
        """One window's batch + the backlog left behind it — the same
        trigger → wait-for-idle → drain shape as collect_batch()."""
        with self.cv:
            self.cv.wait_for(lambda: self.stopping or self.queue)
            batch = []
            if self.stopping and not self.queue:
                return batch, 0
            window_end = time.monotonic() + self.max_s
            while True:
                while self.queue and len(batch) < self.max_batch:
                    batch.append(self.queue.popleft())
                if len(batch) >= self.max_batch or self.stopping:
                    break
                now = time.monotonic()
                if now >= window_end:
                    break
                if not self.cv.wait_for(
                        lambda: self.queue or self.stopping,
                        timeout=min(window_end - now, self.idle_s)):
                    break  # idle gap elapsed with nothing new
            return batch, len(self.queue)


class LoopbackSolverd:
    def __init__(self, socket_path: str, idle_ms: float = 5,
                 max_ms: float = 100, max_batch: int = 64,
                 reset_state: bool = True):
        self.socket_path = socket_path
        self._window = _Window(idle_ms, max_ms, max_batch)
        self._closed = False
        self._conn_seq = 0
        self._threads = []
        self._write_locks = {}
        if reset_state:
            # a loopback start IS a logical worker start: stats must not
            # report a previous harness run's history (the same contract
            # native/solverd.cc applies on boot)
            from karpenter_tpu.service import backend
            backend.reset_worker_state()
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(socket_path)
        self._srv.listen(64)
        self._spawn(self._accept_loop, "loopback-accept")
        self._spawn(self._batcher_loop, "loopback-batcher")

    def _spawn(self, fn, name):
        t = threading.Thread(target=fn, daemon=True, name=name)
        t.start()
        self._threads.append(t)

    # -- socket side -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self._conn_seq += 1
            # bounded reads so close() unwedges reader threads promptly
            conn.settimeout(0.5)
            self._write_locks[conn] = threading.Lock()
            self._spawn(lambda c=conn, i=self._conn_seq:
                        self._reader_loop(c, i), "loopback-reader")

    def _reader_loop(self, conn, conn_id: int) -> None:
        try:
            while not self._closed:
                header = self._read_exact(conn, 12)
                if header is None:
                    return
                plen, rid = struct.unpack("<IQ", header)
                if plen > _MAX_FRAME:
                    return
                payload = self._read_exact(conn, plen)
                if payload is None:
                    return
                self._window.push((conn, conn_id, rid, payload))
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _read_exact(self, conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except socket.timeout:
                if self._closed:
                    return None
                continue
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- the window → backend seam ----------------------------------------
    def _batcher_loop(self) -> None:
        from karpenter_tpu.service import backend
        while not self._closed:
            batch, backlog = self._window.collect()
            if not batch:
                if self._window.stopping:
                    return
                continue
            payloads = [p for _, _, _, p in batch]
            conn_ids = [cid for _, cid, _, _ in batch]
            try:
                outs = backend.handle_batch(payloads, conn_ids, backlog)
            except Exception:  # noqa: BLE001 — answer with the daemon's marker
                outs = [b"\x80\x04N."] * len(batch)
            for (conn, _cid, rid, _p), out in zip(batch, outs):
                frame = struct.pack("<IQ", len(out), rid) + out
                lock = self._write_locks.get(conn)
                try:
                    if lock is not None:
                        with lock:
                            # serializing the WRITE is the point, exactly
                            # as in send_response's write_mu
                            conn.sendall(frame)  # kt-lint: disable=lock-discipline
                    else:
                        conn.sendall(frame)
                except OSError:
                    pass  # peer died; its client reader fails its waiters

    def close(self) -> None:
        self._closed = True
        with self._window.cv:
            self._window.stopping = True
            self._window.cv.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in list(self._write_locks):
            try:
                conn.close()
            except OSError:
                pass
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
