"""Supervised kt_solverd worker (ISSUE 7 tentpole part 1).

The embedded-CPython solver daemon is the one component whose compute
path can take the whole process down (a segfault in XLA, an OOM kill, a
wedged lowering). Crash isolation means the *daemon process* is
disposable: this supervisor owns the socket path's lifecycle, runs
kt_solverd as a child WORKER process, and restarts it on any unexpected
exit with crash-loop backoff. Everything else recovers through contracts
that already exist:

  * in-flight requests — the worker's death closes its connections;
    every client's reader fails its outstanding waiters fast
    (service/client.py `_read_loop`), nothing hangs until timeout
  * catalog state — the restarted worker is empty; clients re-upload on
    demand via the `need_catalog` handshake (their upload ledger is
    per-connection and clears on reconnect)
  * compile state — the persistent JAX compilation cache makes the
    restarted worker's "cold" compiles disk hits

Restart policy: exponential backoff (base·2^streak, capped, jittered) on
consecutive crashes; a worker that stayed up longer than
`backoff_reset` resets the streak, so one crash a day restarts in
`backoff_base` while a crash loop decays to `backoff_max`. Each restart
increments `karpenter_tpu_service_worker_restarts_total`.

Wedge detection (optional, off by default): with `probe_interval` set,
the supervisor periodically opens a fresh connection and sends a
("stats", {}) frame; `probe_failures` consecutive probes with no answer
within `probe_timeout` get the worker killed (and therefore restarted).
The default is off because a cold XLA compile legitimately blocks the
single batcher thread for minutes — enable it only with a
`probe_timeout` comfortably above the worst compile the deployment can
see, or with a warm compilation cache.

Usage (programmatic — tests, operator wiring):

    sup = SolverdSupervisor(socket_path)
    sup.start()
    ...
    sup.stop()

Usage (CLI, the deployment shape):

    python -m karpenter_tpu.service.supervisor --socket /run/kt.sock \\
        [--binary native/build/kt_solverd] [-- --idle-ms 5 --max-ms 100]
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import subprocess
import threading
import time
from typing import Optional, Sequence

from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BINARY = os.path.join(_REPO, "native", "build", "kt_solverd")


class SolverdSupervisor:
    def __init__(self, socket_path: str,
                 binary: Optional[str] = None,
                 extra_args: Sequence[str] = (),
                 env: Optional[dict] = None,
                 stderr_path: Optional[str] = None,
                 backoff_base: float = 0.2,
                 backoff_max: float = 30.0,
                 backoff_reset: float = 60.0,
                 max_restarts: Optional[int] = None,
                 probe_interval: Optional[float] = None,
                 probe_timeout: float = 300.0,
                 probe_failures: int = 3):
        self.socket_path = socket_path
        self.binary = binary or DEFAULT_BINARY
        self.extra_args = list(extra_args)
        self.env = dict(env) if env is not None else dict(os.environ)
        self.stderr_path = stderr_path
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.backoff_reset = backoff_reset
        self.max_restarts = max_restarts
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.probe_failures = max(1, int(probe_failures))
        self.restarts = 0
        self.last_exit: Optional[int] = None
        self.gave_up = False
        self._log = get_logger("solverd-supervisor")
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self, wait_for_socket: bool = True,
              timeout: float = 30.0) -> None:
        if not os.path.exists(self.binary):
            raise FileNotFoundError(
                f"kt_solverd binary missing: {self.binary} "
                "(build it: make -C native solverd)")
        self._stop_ev.clear()
        self.gave_up = False
        self._spawn()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="solverd-supervisor")
        self._monitor.start()
        # forward worker-lifecycle state to the operator's dashboard:
        # in the in-process topology (tests, embedded supervision) the
        # operator's GET /debug/dashboard merges this source; the
        # standalone CLI exports the same numbers via --metrics-port
        from karpenter_tpu.utils import telemetry
        self._telemetry_fn = self.stats  # one bound object: unregister
        telemetry.register_source("supervisor", self._telemetry_fn)
        if wait_for_socket:
            self.wait_ready(timeout)

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until a worker is actually ACCEPTING on the socket. A
        connect probe, not an existence check: a SIGKILLed worker never
        unlinks its socket file, so after a crash (or against a
        persistent volume) the stale file exists long before the
        replacement listens. Returns early once the supervisor has
        given up (`max_restarts`) — callers assert on `gave_up`."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.gave_up or self._stop_ev.is_set():
                return
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(0.5)
            try:
                s.connect(self.socket_path)
                return
            except OSError:
                pass
            finally:
                try:
                    s.close()
                except OSError:
                    pass
            time.sleep(0.05)
        raise TimeoutError(
            f"solverd worker never accepted on {self.socket_path}")

    def stats(self) -> dict:
        """Snapshot for the telemetry merge (utils/telemetry.py): the
        worker-lifecycle state only this process knows — restart count,
        liveness, last exit code, crash-loop give-up."""
        return {
            "restarts": self.restarts,
            "running": self.running,
            "gave_up": self.gave_up,
            "last_exit": self.last_exit,
            "worker_pid": self.worker_pid,
            "socket": self.socket_path,
        }

    def stop(self, timeout: float = 10.0) -> None:
        from karpenter_tpu.utils import telemetry
        fn = getattr(self, "_telemetry_fn", None)
        if fn is not None:
            telemetry.unregister_source("supervisor", fn)
        # order matters: join the monitor FIRST (its waits are all
        # short and stop-aware), THEN kill whatever worker is current —
        # terminating before the join races a backoff-respawn and
        # leaks a live worker holding the socket
        self._stop_ev.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout)
            self._monitor = None
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    def kill_worker(self) -> None:
        """SIGKILL the current worker (fault-matrix harness: sudden
        death mid-batch). The monitor restarts it through the normal
        crash path."""
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()

    @property
    def running(self) -> bool:
        with self._lock:
            proc = self._proc
        return proc is not None and proc.poll() is None

    @property
    def worker_pid(self) -> Optional[int]:
        with self._lock:
            proc = self._proc
        return proc.pid if proc is not None and proc.poll() is None else None

    # -- internals --------------------------------------------------------
    def _spawn(self) -> None:
        argv = [self.binary, "--socket", self.socket_path, *self.extra_args]
        stderr_f = None
        try:
            if self.stderr_path:
                stderr_f = open(self.stderr_path, "ab")
            proc = subprocess.Popen(argv, env=self.env, stderr=stderr_f)
        finally:
            if stderr_f is not None:
                # Popen dup'd the fd into the child; the parent copy
                # closes so repeated restarts can't leak descriptors
                stderr_f.close()
        with self._lock:
            self._proc = proc
        self._log.info("solverd worker started", pid=proc.pid,
                       socket=self.socket_path)

    def _monitor_loop(self) -> None:
        streak = 0
        while not self._stop_ev.is_set():
            with self._lock:
                proc = self._proc
            started = time.monotonic()
            self._await_exit(proc)
            self.last_exit = proc.returncode
            if self._stop_ev.is_set():
                return
            uptime = time.monotonic() - started
            if uptime > self.backoff_reset:
                streak = 0  # it ran healthily; this is a fresh incident
            # decide give-up BEFORE counting/logging a restart: the
            # restart counter and its metric must track restarts that
            # actually happen, and a "restarting" log line for a worker
            # that never comes back misleads whoever tails it
            if self.max_restarts is not None \
                    and self.restarts >= self.max_restarts:
                self.gave_up = True
                self._log.error(
                    "solverd worker died again after max restarts; "
                    "giving up (control plane stays in degraded mode)",
                    exit_code=proc.returncode, restarts=self.restarts)
                return
            delay = min(self.backoff_base * (2 ** streak), self.backoff_max)
            delay *= 1.0 + random.uniform(-0.1, 0.1)
            self._log.warn(
                "solverd worker died; restarting",
                exit_code=proc.returncode, uptime_s=round(uptime, 3),
                backoff_s=round(delay, 3))
            if self._stop_ev.wait(max(0.0, delay)):
                return
            streak += 1
            try:
                self._spawn()
            except OSError as e:
                # binary vanished / fork failed: retry with growing
                # backoff rather than killing the supervisor thread —
                # and do NOT count it: the restart counter/metric track
                # workers that actually came back
                self._log.error("solverd worker respawn failed; will "
                                "retry", error=str(e))
                continue
            self.restarts += 1
            metrics.SERVICE_WORKER_RESTARTS.inc()

    def _await_exit(self, proc: subprocess.Popen) -> None:
        """Wait for the worker to exit; with probing enabled, interleave
        liveness probes and SIGKILL a wedged worker so the wait
        completes through the normal crash path."""
        if self.probe_interval is None:
            while not self._stop_ev.is_set():
                try:
                    proc.wait(timeout=0.5)
                    return
                except subprocess.TimeoutExpired:
                    continue
            proc.poll()
            return
        misses = 0
        last_probe = time.monotonic()
        while not self._stop_ev.is_set():
            try:
                proc.wait(timeout=0.2)
                return
            except subprocess.TimeoutExpired:
                pass
            if time.monotonic() - last_probe < self.probe_interval:
                continue
            last_probe = time.monotonic()
            if self._probe_once():
                misses = 0
            else:
                misses += 1
                self._log.warn("solverd worker probe failed",
                               consecutive=misses,
                               threshold=self.probe_failures)
                if misses >= self.probe_failures:
                    self._log.error(
                        "solverd worker wedged (no answer to stats "
                        "probe); killing for restart", misses=misses)
                    proc.kill()
                    # loop back to proc.wait() which now returns
        proc.poll()

    def _probe_once(self) -> bool:
        """One liveness probe: fresh connection, ("stats", {}) frame,
        wait for any response frame within probe_timeout."""
        payload = pickle.dumps(("stats", {}),
                               protocol=pickle.HIGHEST_PROTOCOL)
        frame = struct.pack("<IQ", len(payload), 0) + payload
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.probe_timeout)
        try:
            s.connect(self.socket_path)
            s.sendall(frame)
            need = 12
            buf = b""
            while len(buf) < need:
                chunk = s.recv(need - len(buf))
                if not chunk:
                    return False
                buf += chunk
            return True
        except OSError:
            return False
        finally:
            try:
                s.close()
            except OSError:
                pass


def _serve_metrics(port: int):
    """Tiny /metrics exporter for the STANDALONE supervisor CLI: the
    worker-restart counter lives in this process, and without an
    endpoint here the documented crash-loop signal would be invisible
    in the deployed topology (the operator replicas export their own
    registries on their own ports)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = metrics.REGISTRY.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrapes are not log events
            pass

    from karpenter_tpu.utils.knobs import bind_host
    srv = ThreadingHTTPServer((bind_host(), port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="supervisor-metrics").start()
    return srv


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.service.supervisor",
        description="Supervise a kt_solverd worker: restart on crash "
                    "with backoff; args after -- pass through to the "
                    "worker.")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--binary", default=None)
    ap.add_argument("--stderr", default=None,
                    help="append worker stderr to this file")
    ap.add_argument("--backoff-base", type=float, default=0.2)
    ap.add_argument("--backoff-max", type=float, default=30.0)
    ap.add_argument("--probe-interval", type=float, default=None)
    ap.add_argument("--probe-timeout", type=float, default=300.0)
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics (worker restart counter) on "
                         "this port; 0 = off")
    ap.add_argument("--tenant-weights-file", default=None,
                    help="tenant-weights config file exported to the "
                         "worker as KARPENTER_TPU_TENANT_WEIGHTS_FILE "
                         "(the env knob KARPENTER_TPU_TENANT_WEIGHTS "
                         "stays the per-tenant override)")
    ap.add_argument("worker_args", nargs="*",
                    help="extra kt_solverd args (after --)")
    args = ap.parse_args(argv)
    env = dict(os.environ)
    if args.tenant_weights_file:
        # export-only, never parsed here: the worker's scheduler.py
        # (the knob's grammar owner) reads and parses the file
        env["KARPENTER_TPU_TENANT_WEIGHTS_FILE"] = (  # kt-lint: disable=env-knob
            args.tenant_weights_file)
    sup = SolverdSupervisor(
        args.socket, binary=args.binary, extra_args=args.worker_args,
        env=env,
        stderr_path=args.stderr, backoff_base=args.backoff_base,
        backoff_max=args.backoff_max, probe_interval=args.probe_interval,
        probe_timeout=args.probe_timeout)
    if args.metrics_port:
        _serve_metrics(args.metrics_port)
    sup.start(wait_for_socket=False)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        sup.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
