"""The solver service boundary (SURVEY §2/§5): control-plane replicas talk
to one TPU-owning solver process over a framed unix socket. The daemon
(`native/solverd.cc`, C++) owns IO, threading, and the request-coalescing
window — the reference's `pkg/batcher` pattern natively — and hands each
batch to `backend.handle_batch` in its embedded interpreter, where
catalog-sharing requests fuse into one vmapped device solve.
"""

from karpenter_tpu.service.client import SolverServiceClient

__all__ = ["SolverServiceClient"]
