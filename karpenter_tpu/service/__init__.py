"""The solver service boundary (SURVEY §2/§5): control-plane replicas talk
to one TPU-owning solver process over a framed unix socket. The daemon
(`native/solverd.cc`, C++) owns IO, threading, and the request-coalescing
window — the reference's `pkg/batcher` pattern natively — and hands each
batch to `backend.handle_batch` in its embedded interpreter, where
catalog-sharing requests fuse into one vmapped device solve.

Crash isolation (ISSUE 7): the daemon runs as a disposable WORKER under
`SolverdSupervisor` (restart-on-crash with backoff); the client carries
the availability layer — shared `RetryPolicy`, `CircuitBreaker`, and
per-request deadlines — so the control plane degrades to its in-process
solver instead of hanging when the worker dies.
"""

from karpenter_tpu.service.client import (
    SolverServiceClient,
    SolverServiceError,
    SolverServiceShed,
    SolverServiceTransportError,
    SolverServiceUnavailable,
)
from karpenter_tpu.service.resilience import CircuitBreaker, RetryPolicy
from karpenter_tpu.service.scheduler import TenantScheduler
from karpenter_tpu.service.supervisor import SolverdSupervisor

__all__ = [
    "SolverServiceClient",
    "SolverServiceError",
    "SolverServiceShed",
    "SolverServiceTransportError",
    "SolverServiceUnavailable",
    "CircuitBreaker",
    "RetryPolicy",
    "SolverdSupervisor",
    "TenantScheduler",
]
