"""TPUCloudProvider — Create/Delete/Get/List/GetInstanceTypes/IsDrifted.

Mirrors the reference implementation's behavior:
  Create     pkg/cloudprovider/cloudprovider.go:80-124 → resolve NodeClass
             (Ready gate :99-102), filter instance types by requirements +
             fits + offering availability (:267-282), then the instance
             provider's launch path (pkg/providers/instance/instance.go:95-117):
             exotic-type deprioritization (:456-477), spot-over-OD choice
             (:372-385), drop spot pricier than cheapest OD (:429-451),
             truncate to 60 types (:54), ranked (type × zone × capacity-type)
             overrides to one fleet call, ICE errors → unavailableOfferings
             (:361-367).
  Delete     batched terminate (terminateinstances.go) — NotFound is success.
  List/Get   tag-scoped instance discovery → NodeClaim reconstruction
             (cloudprovider.go:126-165, :321-375).
  IsDrifted  nodeclass-hash annotation comparison (pkg/cloudprovider/drift.go).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import (
    COND_LAUNCHED,
    InstanceType,
    NodeClaim,
    NodeClass,
)
from karpenter_tpu.models.requirements import Requirement
from karpenter_tpu.providers.fake_cloud import (
    CloudInstance,
    FakeCloud,
    FleetCandidate,
    LaunchTemplateNotFound,
    TAG_CLUSTER,
    TAG_NODECLAIM,
    TAG_NODECLASS,
    TAG_NODEPOOL,
)
from karpenter_tpu.providers.instancetype import InstanceTypeProvider
from karpenter_tpu.utils.cache import UnavailableOfferings

MAX_INSTANCE_TYPES = 60  # pkg/providers/instance/instance.go:54


class CloudProviderError(Exception):
    pass


class NodeClassNotReady(CloudProviderError):
    pass


class InsufficientCapacity(CloudProviderError):
    """Every candidate pool returned an ICE; the claim should retry after
    the unavailable-offering TTL (pkg/cache/cache.go:29)."""


class TPUCloudProvider:
    def __init__(
        self,
        cloud: FakeCloud,
        instance_types: InstanceTypeProvider,
        unavailable: UnavailableOfferings,
        node_classes,  # Store of NodeClass
        cluster_name: str = "default-cluster",
        subnets=None,  # SubnetProvider (optional plumbing)
        launch_templates=None,  # LaunchTemplateProvider
        security_groups=None,  # SecurityGroupProvider (drift inputs)
        images=None,  # ImageProvider (drift inputs)
    ):
        self.cloud = cloud
        self.instance_types = instance_types
        self.unavailable = unavailable
        self.node_classes = node_classes
        self.cluster_name = cluster_name
        self.subnets = subnets
        self.launch_templates = launch_templates
        self.security_groups = security_groups
        self.images = images

    # -- instance types ---------------------------------------------------
    def get_instance_types(self, node_class_ref: str) -> List[InstanceType]:
        nc = self.node_classes.get(node_class_ref)
        if nc is None:
            return []
        return self.instance_types.list(nc)

    # -- create -----------------------------------------------------------
    def create(self, claim: NodeClaim) -> CloudInstance:
        nc: Optional[NodeClass] = self.node_classes.get(claim.node_class_ref)
        if nc is None:
            raise CloudProviderError(
                f"nodeclass {claim.node_class_ref} not found")
        if not nc.ready:
            raise NodeClassNotReady(
                f"nodeclass {nc.name} is not ready")

        types = self._resolve_instance_types(claim, nc)
        if not types:
            raise CloudProviderError(
                "all requested instance types were unavailable during launch")

        candidates = self._fleet_candidates(claim, types, nc)
        try:
            inst, ice = self.cloud.create_fleet(
                candidates, tags=self._tags(claim))
        except LaunchTemplateNotFound as err:
            # a template the cache thought existed is gone: invalidate and
            # retry once (instance.go:107-111)
            if self.launch_templates is not None:
                self.launch_templates.invalidate(str(err))
            candidates = self._fleet_candidates(claim, types, nc)
            inst, ice = self.cloud.create_fleet(
                candidates, tags=self._tags(claim))
        for cap_type, itype, zone in ice:
            self.unavailable.mark_unavailable(cap_type, itype, zone)
        if inst is None:
            raise InsufficientCapacity(
                f"no capacity in {len(ice)} candidate pools")
        if self.subnets is not None:
            chosen_cand = next(
                (c for c in candidates
                 if c.instance_type == inst.instance_type
                 and c.zone == inst.zone
                 and c.capacity_type == inst.capacity_type), None)
            if chosen_cand is not None and chosen_cand.subnet_id:
                self.subnets.update_inflight_ips(chosen_cand.subnet_id)

        by_name = {it.name: it for it in types}
        chosen = by_name[inst.instance_type]
        claim.provider_id = inst.instance_id
        claim.capacity = chosen.capacity
        claim.allocatable = chosen.allocatable()
        claim.launch_time = inst.launch_time
        claim.set_condition(COND_LAUNCHED)
        # stamp resolved single-valued labels onto the claim requirements
        for key, val in self._instance_labels(inst, chosen).items():
            claim.requirements.add(Requirement.single(key, val))
        return inst

    def _resolve_instance_types(self, claim: NodeClaim,
                                nc: NodeClass) -> List[InstanceType]:
        """Filter + order the claim's instance types for launch
        (cloudprovider.go:267-282 + instance.go:389-397)."""
        all_types = {it.name: it for it in self.instance_types.list(nc)}
        wanted = claim.instance_type_options or list(all_types)
        out = []
        for name in wanted:
            it = all_types.get(name)
            if it is None:
                continue
            if not it.requirements.compatible(claim.requirements):
                continue
            if not claim.resource_requests.fits(it.allocatable()):
                continue
            if not it.available_offerings(claim.requirements):
                continue
            out.append(it)
        out = self._filter_exotic(claim, out)
        out = self._prefer_capacity_type(claim, out)
        out.sort(key=lambda it: (
            it.cheapest_offering(claim.requirements).price, it.name))
        return out[:MAX_INSTANCE_TYPES]

    def _filter_exotic(self, claim: NodeClaim,
                       types: List[InstanceType]) -> List[InstanceType]:
        """Drop GPU/accelerator shapes unless requested — launching exotic
        capacity for generic pods wastes money (instance.go:456-477)."""
        if claim.resource_requests.get("gpu") > 0:
            return types
        plain = [it for it in types if it.capacity.get("gpu") == 0]
        return plain or types

    def _prefer_capacity_type(self, claim: NodeClaim,
                              types: List[InstanceType]) -> List[InstanceType]:
        """If the claim allows both spot and on-demand, launch spot, and
        drop spot offerings pricier than the cheapest on-demand
        (instance.go:372-385, :429-451)."""
        ct_req = claim.requirements.get(wellknown.CAPACITY_TYPE_LABEL)
        allows_spot = ct_req is None or ct_req.matches(wellknown.CAPACITY_TYPE_SPOT)
        if not allows_spot:
            return types
        cheapest_od = min(
            (o.price for it in types
             for o in it.available_offerings(claim.requirements)
             if o.capacity_type == wellknown.CAPACITY_TYPE_ON_DEMAND),
            default=None)
        out = []
        for it in types:
            spot_offs = [
                o for o in it.available_offerings(claim.requirements)
                if o.capacity_type == wellknown.CAPACITY_TYPE_SPOT
                and (cheapest_od is None or o.price <= cheapest_od)
            ]
            if spot_offs:
                out.append(it)
        return out or types

    def _fleet_candidates(self, claim: NodeClaim, types: List[InstanceType],
                          nc: Optional[NodeClass] = None,
                          ) -> List[FleetCandidate]:
        """(type × zone × capacity-type) overrides ranked by price, crossed
        with the zonal subnet choice and the per-type launch template — the
        price-capacity-optimized allocation input (instance.go:323-359)."""
        ct_req = claim.requirements.get(wellknown.CAPACITY_TYPE_LABEL)
        allows_spot = ct_req is None or ct_req.matches(wellknown.CAPACITY_TYPE_SPOT)
        zonal = None
        if self.subnets is not None and nc is not None:
            zonal = self.subnets.zonal_subnets_for_launch(nc)
        lt_by_type: Dict[str, str] = {}
        if self.launch_templates is not None and nc is not None:
            for lt_name, cfg in self.launch_templates.ensure_all(
                    nc, types).items():
                for tname in cfg.instance_type_names:
                    lt_by_type[tname] = lt_name

        def mk(it, o) -> Optional[FleetCandidate]:
            subnet_id = None
            if zonal is not None:
                subnet = zonal.get(o.zone)
                if subnet is None:
                    return None  # no launchable subnet in this zone
                subnet_id = subnet.subnet_id
            return FleetCandidate(
                instance_type=it.name, zone=o.zone,
                capacity_type=o.capacity_type, price=o.price,
                subnet_id=subnet_id,
                launch_template=lt_by_type.get(it.name))

        cands = []
        for it in types:
            for o in it.available_offerings(claim.requirements):
                if allows_spot and o.capacity_type != wellknown.CAPACITY_TYPE_SPOT:
                    continue  # spot-capable claims launch spot
                c = mk(it, o)
                if c is not None:
                    cands.append(c)
        if not cands:  # no spot offerings at all — fall back to whatever exists
            for it in types:
                for o in it.available_offerings(claim.requirements):
                    c = mk(it, o)
                    if c is not None:
                        cands.append(c)
        cands.sort(key=lambda c: (c.price, c.instance_type, c.zone))
        return cands

    def _tags(self, claim: NodeClaim) -> Dict[str, str]:
        return {
            TAG_CLUSTER: self.cluster_name,
            TAG_NODEPOOL: claim.nodepool,
            TAG_NODECLAIM: claim.name,
            TAG_NODECLASS: claim.node_class_ref,
        }

    def _instance_labels(self, inst: CloudInstance,
                         it: InstanceType) -> Dict[str, str]:
        labels = {
            wellknown.INSTANCE_TYPE_LABEL: inst.instance_type,
            wellknown.ZONE_LABEL: inst.zone,
            wellknown.CAPACITY_TYPE_LABEL: inst.capacity_type,
        }
        for req in it.requirements:
            if req.is_finite() and len(req.values()) == 1:
                (labels[req.key],) = req.values()
        return labels

    # -- delete / get / list ---------------------------------------------
    def delete(self, claim: NodeClaim) -> bool:
        """NotFound is success (pkg/errors/errors.go)."""
        if claim.provider_id:
            self.cloud.terminate_instances([claim.provider_id])
        return True

    def get(self, provider_id: str) -> Optional[CloudInstance]:
        return self.cloud.get_instance(provider_id)

    def list_instances(self) -> List[CloudInstance]:
        """Cluster-scoped discovery by tag (instance.go:140-160)."""
        return self.cloud.describe_instances(
            tag_filter={TAG_CLUSTER: self.cluster_name})

    # -- drift ------------------------------------------------------------
    def is_drifted(self, claim: NodeClaim) -> Optional[str]:
        """Drift reasons mirror pkg/cloudprovider/drift.go:35-38
        (AMIDrift→ImageDrift, SubnetDrift, SecurityGroupDrift,
        NodeClassDrift): compare the live instance's launch provenance
        against what the nodeclass would resolve today."""
        nc = self.node_classes.get(claim.node_class_ref)
        if nc is None:
            return None
        stamped = claim.meta.annotations.get(wellknown.NODECLASS_HASH_ANNOTATION)
        if stamped is not None and stamped != nc.static_hash():
            return "NodeClassDrift"
        inst = self.get(claim.provider_id) if claim.provider_id else None
        if inst is None:
            return None
        if self.images is not None and inst.image_id is not None:
            wanted = {img.image_id for img in self.images.list(nc)}
            if wanted and inst.image_id not in wanted:
                return "ImageDrift"
        if self.subnets is not None and inst.subnet_id is not None:
            wanted = {s.subnet_id for s in self.subnets.list(nc)}
            if wanted and inst.subnet_id not in wanted:
                return "SubnetDrift"
        if self.security_groups is not None and inst.security_group_ids:
            wanted = {g.group_id for g in self.security_groups.list(nc)}
            if wanted and not set(inst.security_group_ids) <= wanted:
                return "SecurityGroupDrift"
        return None

    # -- liveness ---------------------------------------------------------
    def live(self) -> bool:
        return self.instance_types.live()
