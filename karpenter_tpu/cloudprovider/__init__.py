"""The CloudProvider seam — the big interface between core controllers and
the cloud (reference: pkg/cloudprovider/cloudprovider.go:55-238).
"""

from karpenter_tpu.cloudprovider.provider import (
    CloudProviderError,
    InsufficientCapacity,
    NodeClassNotReady,
    TPUCloudProvider,
    MAX_INSTANCE_TYPES,
)

__all__ = [
    "CloudProviderError",
    "InsufficientCapacity",
    "NodeClassNotReady",
    "TPUCloudProvider",
    "MAX_INSTANCE_TYPES",
]
