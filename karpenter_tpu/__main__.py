"""`python -m karpenter_tpu` — the operator entry point (the reference's
cmd/controller/main.go:31-74 single binary)."""

import sys

from karpenter_tpu.operator.operator import main

if __name__ == "__main__":
    sys.exit(main())
