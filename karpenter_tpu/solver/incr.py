"""Event-driven incremental group index: O(churn) steady-state grouping.

PR 8's delta path made the device solve O(suffix), but every warm pass
still paid O(cluster) on the host to DISCOVER the suffix: the full
``group_pods`` walk, the per-group ``_same_group`` prefix scan, and the
per-node fingerprint sweep all touch every pod/node to resolve a 1%
dirty set.  This module maintains those answers incrementally off the
``SolveCacheFeed`` watch stream instead:

  * ``IncrIndex`` — pod name → group key (``scheduling_group_id``) →
    dense kernel row, plus per-node value fingerprints, updated at
    watch-EVENT time (``SolveCache.invalidate`` with resolved objects).
    A churn pass then resolves its dirty set with O(churn) dict probes.
  * ``build_groups`` — assemble the pass's FFD group list from the
    index: clean rows reuse the record's member lists by reference,
    dirty rows rebuild from survivors + event-carried additions, and
    the result ships with ``IncrHints`` (prefix length + suffix reuse
    map) so ``delta.plan`` skips its per-pass cluster walks entirely.

The index TRUSTS the event stream ("armed" contract): it only engages
when the deployment wires a watch feed (``TPUSolver.incr_arm``, done by
``GatedSolver`` next to its ``SolveCacheFeed``) or the INCR knob forces
it — the walk-based delta plan stays the value-verified default for
callers that mutate inputs without events (the solverd daemon, direct
library use).  Every condition the index cannot follow is a COUNTED
fallback to the existing walk (``INCR_FALLBACK_REASONS`` in
solver/explain.py, ``karpenter_tpu_solver_incr_passes_total``):

  * ``cold``   — no index yet (first pass, eviction, racing retirement)
  * ``flood``  — watch-drain overflow / dirty-set flood: all-dirty
  * ``drift``  — the live pending set disagrees with the event-tracked
    view (pod count mismatch, record replaced under the index)
  * ``pods``   — a names-only invalidation (no objects) the index
    cannot apply, from a caller that predates the object-bearing feed
  * ``nodes``  — any node-set/node-value event dirt: the walk's full
    fingerprint sweep is the authority on node churn
  * ``order``  — the FFD order invariant can't be proven by probes
    alone: a brand-new group key, a representative swap that breaks the
    strict (size, name) descending order, or a priority-band change

All fallbacks are transient: the walk pass that absorbs one publishes a
fresh record, and ``SolveCache.put`` rebuilds the index from it (the
"rebuilt from snapshot" path) under the same generation guard the
classic dirty sets use — an invalidation racing the solve keeps the
index retired rather than ever carrying a stale view.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from karpenter_tpu.scheduling.types import priority_of
from karpenter_tpu.solver.encode import group_order_key


@dataclass
class IncrHints:
    """What an index-resolved pass hands ``delta.plan`` so the plan is
    pure lookups: the record the index mirrors, the group list built
    from it (prefix rows are the record's lists BY REFERENCE), the
    precomputed prefix length and suffix reuse map, and the classic
    dirty snapshot taken ATOMICALLY with the index snapshot (put()
    retires exactly this view)."""
    rec: object
    groups: List[list]
    m: int
    reuse: List[Optional[int]]
    consumed: tuple              # (dirty_pods, dirty_nodes, all_dirty, gen)
    dirty_size: int              # event-dirty names observed (flight stamp)


@dataclass
class _IndexSnapshot:
    """One consistent view of the index for a single pass, copied under
    the cache lock (group assembly then runs lock-free)."""
    rec: object
    base_groups: List[list]
    gid_order: List[int]
    gid_row: Dict[int, int]
    order_keys: List[tuple]
    band: int
    n_pods: int
    dirty_gids: Set[int]
    added: Dict[int, Dict[str, object]]
    removed: Set[str]
    nodes_dirty: bool
    flood: bool
    broken: Optional[str]


class IncrIndex:
    """The event-maintained mirror of one DeltaRecord.  All mutation
    happens under the owning SolveCache's lock (invalidate / put /
    snapshot all hold it); the solver only ever sees `_IndexSnapshot`
    copies."""

    def __init__(self, rec, name_gid: Dict[str, int], n_pods: int,
                 band: int, node_fp: Dict[str, object]):
        self.rec = rec
        self.name_gid = name_gid            # pod name -> gid (all rows)
        self.n_pods = n_pods
        self.band = band
        self.node_fp = node_fp              # node name -> _NodeFP
        # O(G) row structures, rebuilt per record
        self.gid_order: List[int] = []
        self.gid_row: Dict[int, int] = {}
        self.order_keys: List[tuple] = []
        self._index_rows(rec)
        # accumulated event dirt (consumed per engaged pass)
        self.dirty_gids: Set[int] = set()
        self.added: Dict[int, Dict[str, object]] = {}
        self.added_gid: Dict[str, int] = {}
        self.removed: Set[str] = set()
        self.nodes_dirty = False
        self.flood = False
        self.broken: Optional[str] = None

    def _index_rows(self, rec) -> None:
        self.gid_order = [gid for gid, _names in rec.gkeys]
        self.gid_row = {gid: i for i, gid in enumerate(self.gid_order)}
        self.order_keys = [group_order_key(g[0]) for g in rec.groups]

    # -- event application (under the cache lock) -----------------------

    def note_names_only(self) -> None:
        """A names-only invalidation: the index has no objects to apply,
        so its membership view is stale until the next rebuild."""
        self.broken = self.broken or "pods"

    def note_flood(self) -> None:
        self.flood = True

    def _present(self, name: str) -> bool:
        return ((name in self.name_gid and name not in self.removed)
                or name in self.added_gid)

    def _retract_added(self, name: str) -> None:
        """Forget a pending ADD the index is still carrying —
        ordinary absorption of a delete/bind event for a pod that
        never reached a record, not a degrade path (the group it
        touched stays dirty and rebuilds exactly)."""
        gid = self.added_gid.pop(name, None)
        if gid is not None:
            self.added.get(gid, {}).pop(name, None)
            self.dirty_gids.add(gid)

    def apply_pod(self, name: str, obj) -> None:
        """One resolved pod event.  `obj` is the store's CURRENT object
        (None = deleted).  A pod bound to a node has left the pending
        set AND moved its node's capacity — node churn is the walk's
        business, so any bind/unknown-deletion marks nodes dirty.

        MEMBER-ORDER contract: group_pods keeps members in INPUT
        (store) order, so the index may only absorb events whose store
        position it can prove.  A brand-new name appends at the store
        end — mirrored by the added dict's insertion order (events
        arrive in mutation order).  A pending event for a name already
        tracked is ambiguous: an in-place modify KEEPS its position
        while a delete+create MOVES to the end, and the coalesced feed
        cannot tell them apart — counted "order" fallback."""
        present = self._present(name)
        pending = obj is not None and obj.node_name is None
        if pending:
            if present:
                self.broken = self.broken or "order"
                return
            gid = obj.scheduling_group_id()
            self.added.setdefault(gid, {})[name] = obj
            self.added_gid[name] = gid
            self.dirty_gids.add(gid)
            self.n_pods += 1
        else:
            self._retract_added(name)
            if obj is not None:
                self.nodes_dirty = True      # bound: node capacity moved
            if name in self.name_gid and name not in self.removed:
                self.removed.add(name)
                self.dirty_gids.add(self.name_gid[name])
            elif not present and obj is None:
                # deletion of a name the index never tracked: most
                # likely a resident pod freeing node capacity
                self.nodes_dirty = True
            if present:
                self.n_pods -= 1

    def apply_node(self, name: str, obj) -> None:
        """One resolved node event: absorb as spurious iff every value
        the encoding reads off the Node object is unchanged (labels,
        taints, readiness, deletion mark, allocatable).  Available
        capacity is NOT on the object — it moves via resident pod
        binds/deletes, which `apply_pod` marks separately — so value
        equality here means the event was a resync touch."""
        fp = self.node_fp.get(name)
        if obj is None or fp is None:
            self.nodes_dirty = True
            return
        alloc = getattr(fp, "alloc", None)
        if (obj.meta.deleting != fp.deleting or obj.ready != fp.ready
                or obj.labels != fp.labels or obj.taints != fp.taints
                or alloc is None
                or not np.array_equal(
                    np.asarray(obj.allocatable.v, dtype=np.float32),
                    alloc)):
            self.nodes_dirty = True

    def apply_claim(self, name: str) -> None:
        """A nodeclaim event dirties the index only when its name
        shadows an existing node — the same effect the name has on the
        walk's `_nodes_unchanged` check."""
        if name in self.node_fp:
            self.nodes_dirty = True

    # -- snapshot / lifecycle (under the cache lock) --------------------

    def snapshot(self) -> _IndexSnapshot:
        return _IndexSnapshot(
            rec=self.rec, base_groups=self.rec.groups,
            gid_order=self.gid_order, gid_row=self.gid_row,
            order_keys=self.order_keys, band=self.band,
            n_pods=self.n_pods, dirty_gids=set(self.dirty_gids),
            added={g: dict(d) for g, d in self.added.items() if d},
            removed=set(self.removed), nodes_dirty=self.nodes_dirty,
            flood=self.flood, broken=self.broken)

    def dirty_count(self) -> int:
        return (len(self.removed) + len(self.added_gid)
                + len(self.dirty_gids))

    def advance(self, rec) -> bool:
        """Structural O(churn) carry after an index-resolved pass: the
        new record's membership is exactly base ∘ (removed, added) by
        construction, so name_gid updates by the event dirt alone and
        only the O(G) row structures rebuild.  Returns False when the
        O(G) count cross-check disagrees — the caller then pays the
        full rebuild (which a fallback pass pays anyway)."""
        if self.broken or self.flood or self.nodes_dirty:
            return False
        for n in self.removed:
            self.name_gid.pop(n, None)
        self.name_gid.update(self.added_gid)
        expect = sum(len(names) for _gid, names in rec.gkeys)
        if len(self.name_gid) != expect:
            return False
        self.rec = rec
        self.n_pods = expect
        self._index_rows(rec)
        self.dirty_gids.clear()
        self.added.clear()
        self.added_gid.clear()
        self.removed.clear()
        return True


def index_from_record(rec, node_fps=None) -> Optional[IncrIndex]:
    """Full O(cluster) index build from a published DeltaRecord — the
    rebuild-from-snapshot path, paid only on passes that were already
    O(cluster) (cold solves and counted fallbacks).  Returns None for
    records the index cannot mirror (multi-band group lists: the strict
    in-band order invariant is per band, and steady-state churn across
    bands is the walk's business)."""
    bands = {priority_of(g[0]) for g in rec.groups}
    if len(bands) > 1:
        return None
    name_gid: Dict[str, int] = {}
    n_pods = 0
    for gid, names in rec.gkeys:
        for n in names:
            name_gid[n] = gid
        n_pods += len(names)
    node_fp = {fp.name: fp for fp in (node_fps or rec.node_fps)}
    return IncrIndex(rec, name_gid, n_pods,
                     next(iter(bands)), node_fp)


def build_groups(snap: _IndexSnapshot, inp
                 ) -> "Tuple[List[list], int, List[Optional[int]]] | str":
    """Assemble the pass's FFD group list from an index snapshot, or a
    fallback-reason string (every string return is counted by the
    caller).  Clean rows reuse the record's lists by reference; dirty
    rows rebuild as survivors (record order) + event additions
    (store-append order); emptied rows drop.  The FFD order invariant is then proved
    by an O(groups) strict-descending sweep of the (size, name) keys —
    never by re-sorting, which would silently mask a wrong probe."""
    if snap.broken:
        return snap.broken
    if snap.flood:
        return "flood"
    if snap.nodes_dirty:
        return "nodes"
    if snap.n_pods != len(inp.pods):
        return "drift"
    for gid in snap.dirty_gids:
        if gid not in snap.gid_row:
            return "order"      # a brand-new group key appeared
    groups: List[list] = []
    keys: List[tuple] = []
    src_rows: List[Optional[int]] = []   # base row per new row, None=dirty
    m = -1                               # set at the FIRST dirty base row:
    for i, g in enumerate(snap.base_groups):   # a drop shifts every later
        gid = snap.gid_order[i]                # row, so it ends the prefix
        if gid in snap.dirty_gids:             # exactly like a rebuild
            if m < 0:
                m = len(groups)
            members = [p for p in g if p.meta.name not in snap.removed]
            adds = snap.added.get(gid)
            if adds:
                # insertion order IS store-append order (apply_pod's
                # member-order contract) — matching group_pods' input
                # order without sorting
                members.extend(adds.values())
            if not members:
                continue         # emptied class: row drops whole
            rep = members[0]
            if priority_of(rep) != snap.band:
                return "order"   # band flip rides the walk
            groups.append(members)
            keys.append(group_order_key(rep))
            src_rows.append(None)
        else:
            groups.append(g)
            keys.append(snap.order_keys[i])
            src_rows.append(i)
    for i in range(1, len(keys)):
        if not keys[i - 1] > keys[i]:
            return "order"       # strict (size, name) descending broken
    if m < 0:
        m = len(groups)
    reuse = src_rows[m:]
    return groups, m, reuse
