"""The TPU solver — the provisioner's hot path as a batched tensor solve.

Replaces the reference's sequential Go FFD loop
(designs/bin-packing.md:28-42, HOT LOOP #1 in SURVEY §3.2) with a
`lax.scan` over *pod equivalence classes* whose inner step vectorizes the
entire nodes×offerings fill on the MXU-friendly dense arrays built by
`encode.py`:

  * columns — the flattened (nodepool × instance-type × zone × capacity-type)
    offering axis. Labels of a column are single-valued, which makes
    requirement conjunction decomposable: a column is compatible with a
    node's accumulated requirements iff it is compatible with every pod
    group on the node individually. That property is what lets node state
    live as a boolean column mask updated by pure AND — no label algebra on
    device.
  * groups — pods deduplicated by scheduling_key (identical pods are
    interchangeable; the reference exploits the same equivalence when
    batching). 50k pods typically collapse to O(10-100) groups, so the
    sequential scan axis is short while every inner operation is a wide
    vectorized fill.

Topology spread constraints (hostname / zone / capacity-type, maxSkew,
minDomains), required pod anti-affinity, and required pod affinity on
zone/capacity-type (populated-domain restriction or seed pin) are encoded
as per-group domain tensors solved in-kernel (see `ffd.py`); constraint
shapes the encoding can't express — custom topology keys, hostname
co-location seeding, selectors coupling pending groups — raise
`UnsupportedPods` and the provisioner falls
back to the CPU oracle (solver-unavailable ⇒ fall back, never fail —
SURVEY §5).
"""

from karpenter_tpu.solver.solve import TPUSolver, UnsupportedPods

__all__ = ["TPUSolver", "UnsupportedPods"]
