"""The TPU solver — the provisioner's hot path as a batched tensor solve.

Replaces the reference's sequential Go FFD loop
(designs/bin-packing.md:28-42, HOT LOOP #1 in SURVEY §3.2) with a
`lax.scan` over *pod equivalence classes* whose inner step vectorizes the
entire nodes×offerings fill on the MXU-friendly dense arrays built by
`encode.py`:

  * columns — the flattened (nodepool × instance-type × zone × capacity-type)
    offering axis. Labels of a column are single-valued, which makes
    requirement conjunction decomposable: a column is compatible with a
    node's accumulated requirements iff it is compatible with every pod
    group on the node individually. That property is what lets node state
    live as a boolean column mask updated by pure AND — no label algebra on
    device.
  * groups — pods deduplicated by scheduling_key (identical pods are
    interchangeable; the reference exploits the same equivalence when
    batching). 50k pods typically collapse to O(10-100) groups, so the
    sequential scan axis is short while every inner operation is a wide
    vectorized fill.

Topology spread constraints (hostname / zone / capacity-type, maxSkew,
minDomains), required pod anti-affinity, and required pod affinity on
zone/capacity-type (populated-domain restriction or seed pin) are encoded
as per-group domain tensors solved in-kernel (see `ffd.py`); constraint
shapes the encoding can't express — custom topology keys, hostname
co-location seeding, selectors coupling pending groups — raise
`UnsupportedPods` and the provisioner falls
back to the CPU oracle (solver-unavailable ⇒ fall back, never fail —
SURVEY §5).

The package exports resolve LAZILY (PEP 562): `TPUSolver` lives in
`solve.py`, which imports jax at module import time — but the jax-free
submodules (`encode`, `explain`, the reason-code registry the oracle and
the event plumbing draw from) must stay importable without pulling a
multi-second jax import into every process that touches a scheduling
verdict (the store daemon, the CPU-oracle fallback path, the lint
tooling)."""

__all__ = ["TPUSolver", "UnsupportedPods"]


def __getattr__(name):
    if name in __all__:
        from karpenter_tpu.solver import solve as _solve
        return getattr(_solve, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
