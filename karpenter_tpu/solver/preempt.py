"""The preemption planner (ISSUE 16) — minimal lowest-priority victim
sets that seat otherwise-unschedulable higher-priority pods.

Pure planning: nothing here evicts.  ``attach(inp, res)`` runs after a
solve as a pre-pass on the final verdicts — for every stranded pod (or
stranded GANG, which seats atomically or not at all) it walks the ONE
shared victim order (:func:`scheduling.types.preemption_victim_order`,
ascending effective priority, then deletion cost, then name) and
greedily accumulates victims until an existing-capacity-only oracle
trial seats the target, then prunes the set back to minimality.  Both
engines (the TPU solver's ``solve()`` tail and the oracle Scheduler)
attach through this module, so kernel-vs-oracle parity covers the
chosen victims by construction.

Victim discipline mirrors the disruption controller's evictability
rules: daemonsets and ``do-not-disrupt`` pods are never victims, and a
gang victim is the WHOLE gang (PR 14 atomicity — evicting part of a
gang leaves a broken gang running).  Targets whose band has no
strictly-lower-priority victim keep their original verdict; targets
that stay stranded after every candidate victim is hypothetically
evicted get ``PreemptionInsufficient``.

The trial input carries NO nodepools: a pod a new node could seat does
not need preemption (the main solve would have bought the node), so
seating must come from freed existing capacity.  Plans land on
``ScheduleResult.preemptions``; executing them — annotating victims,
draining them through the termination path, recording the ledger rows —
is the Preemption controller's job (controllers/preemption.py).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Set, Tuple

from karpenter_tpu.models.objects import Pod
from karpenter_tpu.scheduling.types import (
    ExistingNode,
    PreemptionPlan,
    ScheduleInput,
    ScheduleResult,
    VictimUnit,
    effective_request,
    gang_of,
    preemption_victim_order,
    priority_of,
)
from karpenter_tpu.solver import explain as explainmod


def _victim_units(inp: ScheduleInput) -> List[VictimUnit]:
    """Evictable resident pods as atomic units: singles, and whole
    gangs (all members, across however many nodes they span)."""
    by_gang: Dict[str, List[Tuple[Pod, str]]] = {}
    singles: List[Tuple[Pod, str]] = []
    for en in inp.existing_nodes:
        # synthetic nodes (charge_pool set — the split/rescue paths
        # present planned claims as existing nodes) hold pods that are
        # not actually running anywhere; they are never victims
        if en.node.meta.deleting or en.charge_pool is not None:
            continue
        for p in en.pods:
            if p.is_daemonset or p.do_not_disrupt():
                continue
            g = gang_of(p)
            if g is not None:
                by_gang.setdefault(g.name, []).append((p, en.name))
            else:
                singles.append((p, en.name))
    units = [VictimUnit(
        name=p.meta.name, priority=priority_of(p), cost=p.deletion_cost(),
        pod_names=(p.meta.name,), node_names=(nn,),
    ) for p, nn in singles]
    for gname, members in by_gang.items():
        units.append(VictimUnit(
            name=f"gang:{gname}",
            priority=max(priority_of(p) for p, _ in members),
            cost=sum(p.deletion_cost() for p, _ in members),
            pod_names=tuple(p.meta.name for p, _ in members),
            node_names=tuple(sorted({nn for _, nn in members})),
            gang=gname,
        ))
    return units


def _target_units(inp: ScheduleInput,
                  res: ScheduleResult) -> List[List[Pod]]:
    """Stranded pods as seat-atomic units (gangs whole), highest
    effective priority first; pods already targeted by an attached plan
    are skipped so re-attachment is idempotent."""
    already = {n for pl in res.preemptions for n in pl.target_pods}
    by_name = {p.meta.name: p for p in inp.pods}
    gangs: Dict[str, List[Pod]] = {}
    targets: List[List[Pod]] = []
    for name in res.unschedulable:
        p = by_name.get(name)
        if p is None or name in already:
            continue
        g = gang_of(p)
        if g is not None:
            gangs.setdefault(g.name, []).append(p)
        else:
            targets.append([p])
    targets.extend(gangs.values())
    targets.sort(key=lambda pods: (-max(priority_of(p) for p in pods),
                                   min(p.meta.name for p in pods)))
    return targets


def _trial_seat(inp: ScheduleInput, res: ScheduleResult,
                target_pods: List[Pod], evicted: Set[str]) -> bool:
    """Would ``target_pods`` seat on EXISTING capacity with ``evicted``
    pod names gone?  Existing-only oracle trial — same engine semantics
    (taints, requirements, topology) as the verdict being overturned,
    via the oracle's internal entry so the trial can never re-plan."""
    from karpenter_tpu.scheduling.oracle import Scheduler
    by_name = {p.meta.name: p for p in inp.pods}
    assigned: Dict[str, List[Pod]] = {}
    for pod_name, node in res.existing_assignments.items():
        p = by_name.get(pod_name)
        if p is not None:
            assigned.setdefault(node, []).append(p)
    exist2 = []
    for en in inp.existing_nodes:
        if en.node.meta.deleting:
            continue
        avail = en.available
        pods2 = []
        for p in en.pods:
            if p.meta.name in evicted:
                avail = avail + effective_request(p)
            else:
                pods2.append(p)
        # this pass's own placements consume headroom too
        for p in assigned.get(en.name, ()):
            if p.meta.name not in evicted:
                avail = avail - effective_request(p)
                pods2.append(p)
        exist2.append(ExistingNode(node=en.node, available=avail,
                                   pods=pods2, charge_pool=en.charge_pool))
    trial = ScheduleInput(
        pods=list(target_pods), nodepools=[], instance_types={},
        existing_nodes=exist2)
    tres = Scheduler(trial)._solve()
    return not tres.unschedulable


def plan(inp: ScheduleInput, res: ScheduleResult
         ) -> Tuple[List[PreemptionPlan], Dict[str, str]]:
    """Plans for every plannable stranded target, plus the
    ``PreemptionInsufficient`` verdicts for targets no victim set can
    seat.  Pure — ``res`` is read, never written."""
    plans: List[PreemptionPlan] = []
    insufficient: Dict[str, str] = {}
    targets = _target_units(inp, res)
    if not targets:
        return plans, insufficient
    units = _victim_units(inp)
    consumed = {u.name for pl in res.preemptions for u in pl.victims}
    evicted: Set[str] = {n for pl in res.preemptions
                         for n in pl.victim_pod_names()}
    for pods in targets:
        tp = max(priority_of(p) for p in pods)
        cands = preemption_victim_order(
            u for u in units
            if u.name not in consumed and u.priority < tp)
        if not cands:
            # nothing strictly below this band is evictable: a plain
            # capacity strand, not a preemption case — keep the verdict
            continue
        chosen: List[VictimUnit] = []
        names = set(evicted)
        seated = False
        for u in cands:
            chosen.append(u)
            names.update(u.pod_names)
            if _trial_seat(inp, res, pods, names):
                seated = True
                break
        if not seated:
            reason = explainmod.make(
                explainmod.PREEMPTION_INSUFFICIENT,
                "preemption insufficient: evicting every lower-priority "
                "pod still cannot seat this pod")
            for p in pods:
                insufficient[p.meta.name] = reason
            continue
        # prune back to minimality: drop any victim whose eviction the
        # seat does not actually need (greedy order can overshoot when a
        # later, larger victim alone frees the decisive node)
        for u in list(chosen):
            rest = set(evicted)
            for w in chosen:
                if w is not u:
                    rest.update(w.pod_names)
            if _trial_seat(inp, res, pods, rest):
                chosen.remove(u)
        target_names = sorted(p.meta.name for p in pods)
        pid = "preempt-" + hashlib.sha1(
            "|".join(target_names).encode()).hexdigest()[:12]
        plans.append(PreemptionPlan(
            plan_id=pid, target_pods=target_names, target_priority=tp,
            victims=list(chosen)))
        for u in chosen:
            consumed.add(u.name)
            evicted.update(u.pod_names)
    return plans, insufficient


def attach(inp: ScheduleInput, res: ScheduleResult) -> ScheduleResult:
    """The pre-pass both engines run on final verdicts: attach plans to
    ``res.preemptions`` and rewrite exhausted targets' verdicts to
    ``PreemptionInsufficient``.  No-op when priority is disabled or
    nothing stranded."""
    from karpenter_tpu.utils.knobs import priority_enabled
    if not res.unschedulable or not priority_enabled():
        return res
    plans, insufficient = plan(inp, res)
    res.preemptions.extend(plans)
    for name, reason in insufficient.items():
        res.unschedulable[name] = reason
    return res
