"""Host-side tensor encoding of a scheduling problem.

Turns a `ScheduleInput` into dense numpy arrays for the device kernel:

  columns  [O]    one per (nodepool, instance type, zone, capacity-type)
                  offering, ordered by nodepool priority (weight desc) —
                  column order IS pool preference order
  groups   [G]    pod equivalence classes in FFD order (size desc)
  group_mask [G,O]  label/taint compatibility of a group's pods with each
                  column (vectorized over the interned label vocabulary —
                  the Python set algebra runs once per (group × key), not
                  per (group × column))
  exist_mask [G,E]  same against existing nodes
  + capacity/price/limit arrays

The encoding is cached against the instance-type list identity and catalog
seqnums by the caller; only group/existing arrays change call to call.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import InstanceType, NodePool, Pod
from karpenter_tpu.models.requirements import Requirements
from karpenter_tpu.models.resources import RESOURCE_AXIS, Resources
from karpenter_tpu.models.taints import tolerates_all
from karpenter_tpu.scheduling.types import (
    ExistingNode,
    ScheduleInput,
    effective_request,
)

R = len(RESOURCE_AXIS)
_ABSENT = -1


@dataclass
class Column:
    pool: str
    pool_idx: int
    type_name: str
    zone: str
    capacity_type: str
    price: float
    labels: Dict[str, str]
    allocatable: Resources
    instance_type: InstanceType


@dataclass
class EncodedProblem:
    # device inputs
    group_req: np.ndarray       # [G, R] f32 — effective per-pod request
    group_count: np.ndarray     # [G] i32
    group_mask: np.ndarray      # [G, O] bool
    exist_mask: np.ndarray      # [G, E] bool
    exist_remaining: np.ndarray # [E, R] f32
    col_alloc: np.ndarray       # [O, R] f32
    col_daemon: np.ndarray      # [O, R] f32 — pool daemonset overhead per column
    col_price: np.ndarray       # [O] f32
    col_pool: np.ndarray        # [O] i32
    pool_limit: np.ndarray      # [P, R] f32 (inf = unlimited)
    # host metadata for decode
    groups: List[List[Pod]] = field(default_factory=list)
    columns: List[Column] = field(default_factory=list)
    existing: List[ExistingNode] = field(default_factory=list)
    pools: List[NodePool] = field(default_factory=list)
    merged_reqs: List[List[Optional[Requirements]]] = field(default_factory=list)  # [G][P]

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    @property
    def n_columns(self) -> int:
        return len(self.columns)


class _Vocab:
    """Interns label strings per key into dense int arrays."""

    def __init__(self) -> None:
        self._ids: Dict[str, Dict[str, int]] = {}
        self._rev_cache: Dict[str, Dict[int, str]] = {}

    def id(self, key: str, value: str) -> int:
        vals = self._ids.setdefault(key, {})
        out = vals.get(value)
        if out is None:
            out = len(vals)
            vals[value] = out
            self._rev_cache.pop(key, None)
        return out

    def lookup(self, key: str, value: str) -> int:
        return self._ids.get(key, {}).get(value, _ABSENT - 1)  # never matches

    def reverse(self, key: str) -> Dict[int, str]:
        rev = self._rev_cache.get(key)
        if rev is None:
            rev = {i: v for v, i in self._ids.get(key, {}).items()}
            self._rev_cache[key] = rev
        return rev


def _label_matrix(
    vocab: _Vocab, keys: Sequence[str], label_dicts: Sequence[Dict[str, str]]
) -> Dict[str, np.ndarray]:
    out = {}
    for key in keys:
        out[key] = np.array(
            [vocab.id(key, d[key]) if key in d else _ABSENT for d in label_dicts],
            dtype=np.int32,
        )
    return out


def _eval_requirements(
    reqs: Requirements,
    vocab: _Vocab,
    matrices: Dict[str, np.ndarray],
    n: int,
) -> np.ndarray:
    """Vectorized `matched_by_labels` over n label-dicts (closed world)."""
    ok = np.ones(n, dtype=bool)
    for req in reqs:
        vals = matrices.get(req.key)
        if vals is None:
            # key absent from every candidate
            if not req.matches_absent():
                return np.zeros(n, dtype=bool)
            continue
        absent = vals == _ABSENT
        if req.is_finite():
            allowed = np.array(
                sorted(vocab.lookup(req.key, v) for v in req.values()),
                dtype=np.int32,
            )
            match = np.isin(vals, allowed)
        else:
            # complement / bounds: evaluate per distinct id (few)
            ids = np.unique(vals[~absent])
            rev = vocab.reverse(req.key)
            good = np.array(
                [i for i in ids if i in rev and req.matches(rev[i])],
                dtype=np.int32,
            )
            match = np.isin(vals, good)
        if req.matches_absent():
            match = match | absent
        else:
            match = match & ~absent
        ok &= match
    return ok


def group_pods(pods: List[Pod]) -> List[List[Pod]]:
    """Equivalence classes in FFD order (size desc, then name for stability)."""
    byid: Dict[int, List[Pod]] = {}
    for pod in pods:
        byid.setdefault(pod.scheduling_group_id(), []).append(pod)
    groups = list(byid.values())
    for g in groups:
        g.sort(key=lambda p: p.meta.name)
    groups.sort(key=lambda g: (g[0].requests.sort_key(), g[0].meta.name),
                reverse=True)
    return groups


@dataclass
class CatalogEncoding:
    """The catalog-side (per-call-invariant) half of the encoding: columns,
    interned label matrices, and capacity/price arrays. Cached by the solver
    across calls — it only changes when the instance-type provider's seqnum
    discipline hands out a new list (SURVEY §7 step 2: uploaded once per
    change, not per call)."""
    pools: List[NodePool]
    columns: List[Column]
    vocab: _Vocab
    col_matrices: Dict[str, np.ndarray]
    col_alloc: np.ndarray
    col_daemon: np.ndarray
    col_price: np.ndarray
    col_pool: np.ndarray
    pool_daemon: np.ndarray
    templates: List[Requirements]
    # per pool: column index array, per-key sliced label matrices, and the
    # set of keys its columns actually provide (non-absent somewhere)
    pool_cols: List[np.ndarray] = field(default_factory=list)
    pool_matrices: List[Dict[str, np.ndarray]] = field(default_factory=list)
    pool_provides: List[set] = field(default_factory=list)
    device_args: Optional[dict] = None  # device-resident padded arrays


def encode_catalog(inp: ScheduleInput) -> CatalogEncoding:
    pools = sorted(inp.nodepools, key=lambda np_: (-np_.weight, np_.meta.name))
    vocab = _Vocab()
    columns: List[Column] = []
    for pidx, pool in enumerate(pools):
        for it in inp.instance_types.get(pool.name, []):
            base_labels: Dict[str, str] = {}
            for req in it.requirements:
                if req.is_finite() and len(req.values()) == 1:
                    (base_labels[req.key],) = req.values()
            for o in it.offerings:
                if not o.available:
                    continue
                labels = dict(base_labels)
                labels[wellknown.ZONE_LABEL] = o.zone
                labels[wellknown.CAPACITY_TYPE_LABEL] = o.capacity_type
                labels[wellknown.NODEPOOL_LABEL] = pool.name
                labels.update(pool.labels)
                columns.append(Column(
                    pool=pool.name, pool_idx=pidx, type_name=it.name,
                    zone=o.zone, capacity_type=o.capacity_type, price=o.price,
                    labels=labels, allocatable=it.allocatable(),
                    instance_type=it,
                ))
    col_keys = sorted({k for c in columns for k in c.labels})
    col_matrices = _label_matrix(vocab, col_keys, [c.labels for c in columns])
    O = len(columns)
    col_alloc = np.array([c.allocatable.v for c in columns],
                         dtype=np.float32).reshape(O, R)
    col_daemon = np.zeros((O, R), dtype=np.float32)
    for ci, c in enumerate(columns):
        d = inp.daemon_overhead.get(c.pool)
        if d is not None:
            col_daemon[ci] = np.array(d.v, dtype=np.float32)
    col_price = np.array([c.price for c in columns], dtype=np.float32)
    col_pool = np.array([c.pool_idx for c in columns], dtype=np.int32)
    pool_daemon = np.stack([
        np.array(inp.daemon_overhead.get(p.name, Resources()).v, dtype=np.float32)
        for p in pools]) if pools else np.zeros((1, R), np.float32)
    pool_cols, pool_matrices, pool_provides = [], [], []
    for pidx in range(len(pools)):
        sel = np.nonzero(col_pool == pidx)[0]
        sliced = {k: v[sel] for k, v in col_matrices.items()}
        pool_cols.append(sel)
        pool_matrices.append(sliced)
        pool_provides.append({k for k, v in sliced.items() if (v != _ABSENT).any()})
    return CatalogEncoding(
        pools=pools, columns=columns, vocab=vocab, col_matrices=col_matrices,
        col_alloc=col_alloc, col_daemon=col_daemon, col_price=col_price,
        col_pool=col_pool, pool_daemon=pool_daemon,
        templates=[p.template_requirements() for p in pools],
        pool_cols=pool_cols, pool_matrices=pool_matrices,
        pool_provides=pool_provides,
    )


def encode(inp: ScheduleInput, cat: Optional[CatalogEncoding] = None) -> EncodedProblem:
    cat = cat or encode_catalog(inp)
    pools = cat.pools
    vocab = cat.vocab
    columns = cat.columns
    col_matrices = cat.col_matrices
    groups = group_pods(inp.pods)

    O = len(columns)
    E = len(inp.existing_nodes)
    G = len(groups)

    # existing-node labels (hostnames are per-node-unique) go into a
    # per-call vocab so node churn can't grow the cached catalog vocab
    exist_vocab = _Vocab()
    exist_keys = sorted({k for en in inp.existing_nodes for k in en.node.labels})
    exist_matrices = _label_matrix(
        exist_vocab, exist_keys, [en.node.labels for en in inp.existing_nodes])

    group_req = np.zeros((G, R), dtype=np.float32)
    group_count = np.zeros(G, dtype=np.int32)
    group_mask = np.zeros((G, O), dtype=bool)
    exist_mask = np.zeros((G, E), dtype=bool)
    merged_reqs: List[List[Optional[Requirements]]] = []

    pool_col = cat.col_pool

    for gi, g in enumerate(groups):
        rep = g[0]
        group_req[gi] = np.array(effective_request(rep).v, dtype=np.float32)
        group_count[gi] = len(g)

        merged_per_pool: List[Optional[Requirements]] = []
        gmask = np.zeros(O, dtype=bool)
        for pidx, pool in enumerate(pools):
            if not tolerates_all(pool.taints, rep.tolerations):
                merged_per_pool.append(None)
                continue
            template = cat.templates[pidx]
            if not template.compatible(rep.requirements):
                merged_per_pool.append(None)
                continue
            merged = template.intersection(rep.requirements)
            merged_per_pool.append(merged)
            sel = cat.pool_cols[pidx]
            if len(sel) == 0:
                continue
            # Split merged requirements three ways (oracle's open-world type
            # check, tensorized):
            #   column-provided key   → vectorized closed-world check
            #   template-provided key → already validated by the template ∩
            #                           pod intersection; the node itself
            #                           will carry the label
            #   neither               → satisfiable only by absence
            col_checked = Requirements()
            feasible = True
            for req_ in merged:
                if req_.key in cat.pool_provides[pidx]:
                    col_checked.add(req_)
                elif template.get(req_.key) is not None:
                    continue
                elif not req_.matches_absent():
                    feasible = False
                    break
            if not feasible:
                continue
            ok = _eval_requirements(col_checked, vocab,
                                    cat.pool_matrices[pidx], len(sel))
            gmask[sel[ok]] = True
        group_mask[gi] = gmask
        merged_reqs.append(merged_per_pool)

        if E:
            ok = _eval_requirements(rep.requirements, exist_vocab,
                                    exist_matrices, E)
            for ei, en in enumerate(inp.existing_nodes):
                if not ok[ei]:
                    continue
                node = en.node
                if node.meta.deleting or not node.ready:
                    ok[ei] = False
                elif not tolerates_all(node.taints, rep.tolerations):
                    ok[ei] = False
            exist_mask[gi] = ok

    exist_remaining = np.array(
        [en.available.v for en in inp.existing_nodes], dtype=np.float32
    ).reshape(E, R)

    pool_limit = np.full((max(len(pools), 1), R), np.inf, dtype=np.float32)
    for pidx, pool in enumerate(pools):
        lim = inp.remaining_limits.get(pool.name)
        if lim is not None:
            pool_limit[pidx] = np.array(lim.v, dtype=np.float32)

    return EncodedProblem(
        group_req=group_req,
        group_count=group_count,
        group_mask=group_mask,
        exist_mask=exist_mask,
        exist_remaining=exist_remaining,
        col_alloc=cat.col_alloc,
        col_daemon=cat.col_daemon,
        col_price=cat.col_price,
        col_pool=pool_col,
        pool_limit=pool_limit,
        groups=groups,
        columns=columns,
        existing=list(inp.existing_nodes),
        pools=pools,
        merged_reqs=merged_reqs,
    )


def bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Round up to a fixed shape tier to avoid XLA recompiles
    (ragged-size discipline per SURVEY §7 hard-parts)."""
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** np.ceil(np.log2(max(n, 1))))
