"""Host-side tensor encoding of a scheduling problem.

Turns a `ScheduleInput` into dense numpy arrays for the device kernel:

  columns  [O]    one per (nodepool, instance type, zone, capacity-type)
                  offering, ordered by nodepool priority (weight desc) —
                  column order IS pool preference order
  groups   [G]    pod equivalence classes in FFD order (size desc)
  group_mask [G,O]  label/taint compatibility of a group's pods with each
                  column (vectorized over the interned label vocabulary —
                  the Python set algebra runs once per (group × key), not
                  per (group × column))
  exist_cap [G,E]   per-existing-node pod allowance (0 = blocked; also
                  carries hostname-spread / anti-affinity per-node caps)
  + capacity/price/limit arrays

The encoding is cached against the instance-type list identity and catalog
seqnums by the caller; only group/existing arrays change call to call.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import InstanceType, NodePool, Pod
from karpenter_tpu.models.requirements import Requirements
from karpenter_tpu.models.resources import RESOURCE_AXIS, Resources
from karpenter_tpu.models.taints import tolerates_all
from karpenter_tpu.scheduling.topology import TopologyTracker, node_domains_for
from karpenter_tpu.solver.explain import EPS
from karpenter_tpu.scheduling.types import (
    ExistingNode,
    ScheduleInput,
    effective_request,
    gang_of,
    gang_trial_order,
    priority_of,
)

R = len(RESOURCE_AXIS)
_ABSENT = -1
BIG = 2 ** 29  # "unbounded" cap that still fits i32 arithmetic on device
D_BUCKETS = (2, 4, 8, 16, 32, 64, 128)
_DOM_KEYS = (wellknown.ZONE_LABEL, wellknown.CAPACITY_TYPE_LABEL)
_TOPO_KEYS = (wellknown.HOSTNAME_LABEL,) + _DOM_KEYS


class Unsupported(Exception):
    """A group's topology constraints can't be expressed in the tensor
    encoding (cross-group coupling, required pod affinity, custom topology
    keys) — the caller falls back to the CPU oracle."""


@dataclass
class Column:
    pool: str
    pool_idx: int
    type_name: str
    zone: str
    capacity_type: str
    price: float
    labels: Dict[str, str]
    allocatable: Resources
    instance_type: InstanceType


@dataclass
class EncodedProblem:
    # device inputs
    group_req: np.ndarray       # [G, R] f32 — effective per-pod request
    group_count: np.ndarray     # [G] i32
    group_mask: np.ndarray      # [G, O] bool
    exist_cap: np.ndarray       # [G, E] i32 — per-node allowance (0 = blocked)
    exist_remaining: np.ndarray # [E, R] f32
    col_alloc: np.ndarray       # [O, R] f32
    col_daemon: np.ndarray      # [O, R] f32 — pool daemonset overhead per column
    col_price: np.ndarray       # [O] f32
    col_pool: np.ndarray        # [O] i32
    pool_limit: np.ndarray      # [P, R] f32 (inf = unlimited)
    # topology tensors (see solver/ffd.py docstring)
    group_ncap: np.ndarray = None    # [G] i32 per-new-node cap
    group_dsel: np.ndarray = None    # [G] i32 0 none / 1 zone / 2 capacity-type
    group_dbase: np.ndarray = None   # [G, D] i32
    group_dcap: np.ndarray = None    # [G, D] i32
    group_skew: np.ndarray = None    # [G] i32
    group_mindom: np.ndarray = None  # [G] i32
    group_delig: np.ndarray = None   # [G, D] bool
    # [G] bool — hostname co-location seeding: ALL members must land on
    # one node.  Encode-time column/row fit enforces it against original
    # capacity; the post-solve whole-node repair (solve.py) strands the
    # group atomically if the dynamic fill still split it
    group_whole_node: np.ndarray = None
    # [G] bool — gang unit (ISSUE 15): atomic K-node, single-adjacency-
    # domain placement.  For gang groups, group_dsel names the adjacency
    # axis (1 zone/slice, 2 capacity-type/rack, 0 none) and group_dbase
    # carries the lexicographic domain trial RANK (gang_trial_order),
    # not spread base counts; skew/mindom/dcap stay inert.
    group_gang: np.ndarray = None
    # [G] i32 — effective priority per group (ISSUE 16).  The groups
    # list is already in band order (group_pods' host-side stable
    # re-sort, highest band first); this row is the kernel's witness
    # input (with_priority inversion aux) and decode's band map.
    group_priority: np.ndarray = None
    # [O] f32 — decode RANKING price (= col_price unless the spot-risk
    # objective is on; see CatalogEncoding.col_price_eff)
    col_price_eff: np.ndarray = None
    col_zone: np.ndarray = None      # [O] i32
    col_ct: np.ndarray = None        # [O] i32
    exist_zone: np.ndarray = None    # [E] i32
    exist_ct: np.ndarray = None      # [E] i32
    zone_values: List[str] = field(default_factory=list)  # id → zone
    ct_values: List[str] = field(default_factory=list)    # id → capacity type
    n_domains: int = 1
    # per group: static allowed-domain id sets (None = unrestricted) — folded
    # into the column masks for the solve AND into claim requirements at
    # decode, so launch can't drift into a statically-forbidden domain
    static_allowed: List[Dict[str, Optional[set]]] = field(default_factory=list)
    # split mode (encode(split=True)): groups whose constraints the tensor
    # encoding can't express, with the reason — solved host-side AFTER the
    # device solve instead of abandoning the whole batch (VERDICT r1 #4)
    residue: List[Tuple[List[Pod], str]] = field(default_factory=list)
    # placement provenance (solver/explain.py HOST_CONSTRAINTS): per
    # group, columns eliminated by [compat mask, price cap] — filled by
    # the solver's _encode_checked when KARPENTER_TPU_EXPLAIN is armed
    # (the cap is folded into group_mask before the kernel ever sees it,
    # so the split must be taken host-side)
    explain_host: Optional[np.ndarray] = None   # [G, 2] i64
    # the price cap that was folded into group_mask (None = uncapped) —
    # the explainer's price nearest-miss needs the value back out
    explain_price_cap: Optional[float] = None
    # host metadata for decode
    groups: List[List[Pod]] = field(default_factory=list)
    columns: List[Column] = field(default_factory=list)
    existing: List[ExistingNode] = field(default_factory=list)
    pools: List[NodePool] = field(default_factory=list)
    merged_reqs: List[List[Optional[Requirements]]] = field(default_factory=list)  # [G][P]

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    @property
    def n_columns(self) -> int:
        return len(self.columns)


class _Vocab:
    """Interns label strings per key into dense int arrays."""

    def __init__(self) -> None:
        self._ids: Dict[str, Dict[str, int]] = {}
        self._rev_cache: Dict[str, Dict[int, str]] = {}

    def id(self, key: str, value: str) -> int:
        vals = self._ids.setdefault(key, {})
        out = vals.get(value)
        if out is None:
            out = len(vals)
            vals[value] = out
            self._rev_cache.pop(key, None)
        return out

    def lookup(self, key: str, value: str) -> int:
        return self._ids.get(key, {}).get(value, _ABSENT - 1)  # never matches

    def reverse(self, key: str) -> Dict[int, str]:
        rev = self._rev_cache.get(key)
        if rev is None:
            rev = {i: v for v, i in self._ids.get(key, {}).items()}
            self._rev_cache[key] = rev
        return rev


def _label_matrix(
    vocab: _Vocab, keys: Sequence[str], label_dicts: Sequence[Dict[str, str]]
) -> Dict[str, np.ndarray]:
    out = {}
    for key in keys:
        out[key] = np.array(
            [vocab.id(key, d[key]) if key in d else _ABSENT for d in label_dicts],
            dtype=np.int32,
        )
    return out


def _eval_requirements(
    reqs: Requirements,
    vocab: _Vocab,
    matrices: Dict[str, np.ndarray],
    n: int,
) -> np.ndarray:
    """Vectorized `matched_by_labels` over n label-dicts (closed world)."""
    ok = np.ones(n, dtype=bool)
    for req in reqs:
        vals = matrices.get(req.key)
        if vals is None:
            # key absent from every candidate
            if not req.matches_absent():
                return np.zeros(n, dtype=bool)
            continue
        absent = vals == _ABSENT
        if req.is_finite():
            allowed = np.array(
                sorted(vocab.lookup(req.key, v) for v in req.values()),
                dtype=np.int32,
            )
            match = np.isin(vals, allowed)
        else:
            # complement / bounds: evaluate per distinct id (few)
            ids = np.unique(vals[~absent])
            rev = vocab.reverse(req.key)
            good = np.array(
                [i for i in ids if i in rev and req.matches(rev[i])],
                dtype=np.int32,
            )
            match = np.isin(vals, good)
        if req.matches_absent():
            match = match | absent
        else:
            match = match & ~absent
        ok &= match
    return ok


def exist_group_ok(rep: Pod, vocab: "_Vocab",
                   matrices: Dict[str, np.ndarray],
                   existing: Sequence[ExistingNode]) -> np.ndarray:
    """Per-existing-node eligibility verdict for one pod class:
    requirements-matched ∧ not-deleting ∧ ready ∧ taints-tolerated.
    ONE definition shared by encode()'s per-group loop and the delta
    path's re-encode of a changed group (solver/delta.py) — the delta
    contract is bit-parity with a full re-solve, so the two must never
    drift."""
    ok = _eval_requirements(rep.requirements, vocab, matrices,
                            len(existing))
    for ei, en in enumerate(existing):
        if not ok[ei]:
            continue
        node = en.node
        if node.meta.deleting or not node.ready:
            ok[ei] = False
        elif not tolerates_all(node.taints, rep.tolerations):
            ok[ei] = False
    return ok


def group_pods(pods: List[Pod]) -> List[List[Pod]]:
    """Equivalence classes in FFD order (size desc, then name for stability).

    The C++ fast path (native/hostops.cc) carries the identical contract;
    at 50k pods the Python loop costs more than the device solve, so this
    is part of the native solver boundary (SURVEY §2). `group_pods_py` is
    the fallback and the differential-test oracle."""
    from karpenter_tpu.native import hostops
    native = hostops()
    groups = (native.group_pods(pods) if native is not None
              else group_pods_py(pods))
    return _priority_band_sort(groups)


def _priority_band_sort(groups: List[List[Pod]]) -> List[List[Pod]]:
    """Stable re-sort of equivalence classes into strict priority-band
    order, highest band first (ISSUE 16): the kernel scans groups in
    list order, so putting a band's groups first IS the packing policy —
    higher bands consume existing capacity, pool limits, and node slots
    before lower bands see them.  Applied AFTER either grouping path
    (native or Python) as a host-side post-pass: the stable sort keeps
    the FFD order (size desc, name) intact WITHIN each band, and an
    all-one-band problem (every effective priority equal — the
    priority-free common case) comes back ordered exactly as it went in,
    preserving bit parity with the pre-priority pipeline.  Groups are
    priority-homogeneous by construction (the effective priority joins
    the scheduling key)."""
    prios = [priority_of(g[0]) for g in groups]
    if len(set(prios)) <= 1:
        return groups
    order = sorted(range(len(groups)), key=lambda i: -prios[i])
    return [groups[i] for i in order]


def group_order_key(rep: Pod) -> tuple:
    """The FFD ordering key of one equivalence class, read off its
    representative: size descending with the representative's name as
    the deterministic tiebreak.  The ONE definition shared by the
    grouping sort below, the native fast path's contract, and the
    event-driven index (solver/incr.py) — the index proves the order
    invariant by comparing these keys, so a private copy drifting in
    either place would let an out-of-order group list engage the
    seeded replay."""
    return (rep.requests.sort_key(), rep.meta.name)


def group_pods_py(pods: List[Pod]) -> List[List[Pod]]:
    byid: Dict[int, List[Pod]] = {}
    for pod in pods:
        byid.setdefault(pod.scheduling_group_id(), []).append(pod)
    groups = list(byid.values())
    # members keep INPUT order (deterministic: both solver paths group the
    # same list, and pods within a class are interchangeable) — the old
    # per-member name sort was ~40% of grouping cost at 50k pods for a
    # purely cosmetic ordering
    groups.sort(key=lambda g: group_order_key(g[0]), reverse=True)
    return groups


@dataclass
class CatalogEncoding:
    """The catalog-side (per-call-invariant) half of the encoding: columns,
    interned label matrices, and capacity/price arrays. Cached by the solver
    across calls — it only changes when the instance-type provider's seqnum
    discipline hands out a new list (SURVEY §7 step 2: uploaded once per
    change, not per call)."""
    pools: List[NodePool]
    columns: List[Column]
    vocab: _Vocab
    col_matrices: Dict[str, np.ndarray]
    col_alloc: np.ndarray
    col_daemon: np.ndarray
    col_price: np.ndarray
    col_pool: np.ndarray
    pool_daemon: np.ndarray
    templates: List[Requirements]
    # per pool: column index array, per-key sliced label matrices, and the
    # set of keys its columns actually provide (non-absent somewhere)
    pool_cols: List[np.ndarray] = field(default_factory=list)
    pool_matrices: List[Dict[str, np.ndarray]] = field(default_factory=list)
    pool_provides: List[set] = field(default_factory=list)
    # topology domain interning (zone / capacity-type → dense id)
    zone_ids: Dict[str, int] = field(default_factory=dict)
    ct_ids: Dict[str, int] = field(default_factory=dict)
    col_zone: np.ndarray = None  # [O] i32
    col_ct: np.ndarray = None    # [O] i32
    # capacity dedup: allocatable varies only per (pool, instance type) —
    # the column axis is a fixed-stride grid of ZC (zone, capacity-type)
    # pairs per (pool,type) block, so the kernel's fit math runs at
    # [N,PT] (= [N,O/ZC]) via pure reshapes. Grid combos with no
    # available offering are masked out by col_valid.
    zc: int = 1                  # grid stride (len of the zone×ct grid)
    pt_alloc: np.ndarray = None  # [PT, R] f32 (PT = O // zc)
    col_valid: np.ndarray = None # [O] bool
    # [O] f32 — the RANKING price (ISSUE 16): equal to col_price unless
    # the KARPENTER_TPU_SPOT_RISK objective is on, in which case spot
    # columns carry price*(1+λ·p_interrupt) (scheduling/risk.py).  A
    # ranking key ONLY — col_price, Column.price, claims, and the ledger
    # always keep the real offering price.  Cache-safe: risk.model_key()
    # joins the solver's catalog-encoding cache key, so an interruption
    # observation rebuilds this encoding rather than mutating it.
    col_price_eff: np.ndarray = None
    # real offerings / grid columns — how much of the column axis is
    # masked-out inflation; layout is "grid" or "dense" (the fallback)
    fill_factor: float = 1.0
    layout: str = "grid"
    device_args: Optional[dict] = None  # device-resident padded arrays


def encode_catalog(inp: ScheduleInput) -> CatalogEncoding:
    """Column layout is a FIXED-STRIDE grid: for every (pool, type) block,
    one column per (zone, capacity-type) pair of the global grid, in grid
    order — combos with no available offering become masked-out columns
    (col_valid False, price inf) instead of being skipped. The uniform
    stride ZC is what lets the kernel run its capacity math at (pool,type)
    granularity with pure reshapes (no scatter/segment ops): allocatable
    only varies per type, so zones × capacity-types were repeating the
    same fit computation ~ZC times."""
    pools = sorted(inp.nodepools, key=lambda np_: (-np_.weight, np_.meta.name))
    vocab = _Vocab()
    zc_pairs = sorted({
        (o.zone, o.capacity_type)
        for p in pools for it in inp.instance_types.get(p.name, [])
        for o in it.offerings})
    # grid fill factor: the global (zone, ct) pair set replicates per
    # (pool,type) block, so zone-disjoint pools / capacity-type-disjoint
    # types inflate O with masked-out columns (ADVICE r3). When the grid
    # would be mostly dead, fall back to a DENSE layout — one column per
    # real offering, zc=1 — which keeps every downstream reshape valid
    # (PT == O) at the cost of per-column instead of per-block fit math.
    n_blocks = sum(len(inp.instance_types.get(p.name, [])) for p in pools)
    n_real = sum(len(it.offerings)
                 for p in pools for it in inp.instance_types.get(p.name, []))
    grid_cols = n_blocks * max(len(zc_pairs), 1)
    fill = (n_real / grid_cols) if grid_cols else 1.0
    dense = grid_cols > 512 and fill < 0.5
    columns: List[Column] = []
    col_valid_list: List[bool] = []
    for pidx, pool in enumerate(pools):
        for it in inp.instance_types.get(pool.name, []):
            base_labels: Dict[str, str] = {}
            for req in it.requirements:
                if req.is_finite() and len(req.values()) == 1:
                    (base_labels[req.key],) = req.values()
            offmap = {(o.zone, o.capacity_type): o for o in it.offerings}
            alloc = it.allocatable()
            pairs = (sorted(offmap) if dense else zc_pairs)
            for zone, ct in pairs:
                o = offmap.get((zone, ct))
                labels = dict(base_labels)
                labels[wellknown.ZONE_LABEL] = zone
                labels[wellknown.CAPACITY_TYPE_LABEL] = ct
                labels[wellknown.NODEPOOL_LABEL] = pool.name
                labels.update(pool.labels)
                columns.append(Column(
                    pool=pool.name, pool_idx=pidx, type_name=it.name,
                    zone=zone, capacity_type=ct,
                    price=(o.price if o is not None else float("inf")),
                    labels=labels, allocatable=alloc,
                    instance_type=it,
                ))
                col_valid_list.append(o is not None and o.available)
    col_keys = sorted({k for c in columns for k in c.labels})
    col_matrices = _label_matrix(vocab, col_keys, [c.labels for c in columns])
    O = len(columns)
    col_alloc = np.array([c.allocatable.v for c in columns],
                         dtype=np.float32).reshape(O, R)
    col_daemon = np.zeros((O, R), dtype=np.float32)
    for ci, c in enumerate(columns):
        d = inp.daemon_overhead.get(c.pool)
        if d is not None:
            col_daemon[ci] = np.array(d.v, dtype=np.float32)
    col_price = np.array([c.price for c in columns], dtype=np.float32)
    from karpenter_tpu.utils.knobs import spot_risk_enabled
    if spot_risk_enabled():
        from karpenter_tpu.scheduling import risk
        col_price_eff = np.array(
            [risk.effective_price(c.price, c.type_name, c.zone,
                                  c.capacity_type)
             for c in columns], dtype=np.float32)
    else:
        col_price_eff = col_price
    col_pool = np.array([c.pool_idx for c in columns], dtype=np.int32)
    pool_daemon = np.stack([
        np.array(inp.daemon_overhead.get(p.name, Resources()).v, dtype=np.float32)
        for p in pools]) if pools else np.zeros((1, R), np.float32)
    pool_cols, pool_matrices, pool_provides = [], [], []
    for pidx in range(len(pools)):
        sel = np.nonzero(col_pool == pidx)[0]
        sliced = {k: v[sel] for k, v in col_matrices.items()}
        pool_cols.append(sel)
        pool_matrices.append(sliced)
        pool_provides.append({k for k, v in sliced.items() if (v != _ABSENT).any()})
    zone_ids: Dict[str, int] = {}
    ct_ids: Dict[str, int] = {}
    for c in columns:
        zone_ids.setdefault(c.zone, len(zone_ids))
        ct_ids.setdefault(c.capacity_type, len(ct_ids))
    col_zone = np.array([zone_ids[c.zone] for c in columns], dtype=np.int32)
    col_ct = np.array([ct_ids[c.capacity_type] for c in columns], dtype=np.int32)
    zc = 1 if dense else max(len(zc_pairs), 1)
    pt_alloc = (col_alloc[::zc].copy() if O
                else np.zeros((0, R), dtype=np.float32))
    col_valid = np.array(col_valid_list, dtype=bool)
    return CatalogEncoding(
        pools=pools, columns=columns, vocab=vocab, col_matrices=col_matrices,
        col_alloc=col_alloc, col_daemon=col_daemon, col_price=col_price,
        col_pool=col_pool, pool_daemon=pool_daemon,
        templates=[p.template_requirements() for p in pools],
        pool_cols=pool_cols, pool_matrices=pool_matrices,
        pool_provides=pool_provides,
        zone_ids=zone_ids, ct_ids=ct_ids, col_zone=col_zone, col_ct=col_ct,
        zc=zc, pt_alloc=pt_alloc, col_valid=col_valid,
        col_price_eff=col_price_eff,
        fill_factor=round(fill, 4), layout=("dense" if dense else "grid"),
    )


def _matches(sel: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in sel.items())


def _has_required_anti(pods) -> bool:
    """Whether any resident pod's required anti-affinity can constrain
    pending pods (the only way existing state constrains otherwise-
    unconstrained pods — the k8s symmetry rule). ONE definition shared by
    the union cache, its divergent-wrapper fallback, and the per-sim
    topology encoder: these must never disagree."""
    return any(t.required and t.anti
               for p in pods for t in p.pod_affinities)


class SharedExistEncoding:
    """Union cache of existing-node encodings for ONE solve_batch call.

    The consolidation sweep (SURVEY §3.3 hot loop #2) encodes ~N
    near-identical node sets N times — at 2k candidates × 2k nodes the
    per-simulation label interning and per-node Python checks dominate
    the whole sweep (profiled: ~85% of wall-clock). Everything determined
    by the Node object alone — label matrices, readiness, zone/ct ids,
    per-group requirement+toleration verdicts — is computed once over
    the union of nodes and gathered per simulation by row index.

    Sound only within one batch: TPUSolver.solve_batch's contract is
    that all inputs come from the same cluster snapshot, so a node
    object's labels/taints/readiness — and its resident-pod set, which
    the required-anti activity check reads — are fixed for the batch.
    """

    def __init__(self, cat: "CatalogEncoding"):
        self._index: Dict[int, int] = {}
        # strong refs: id() keys stay unambiguous while the cache lives
        self._nodes: List = []
        self._wrappers: List[ExistingNode] = []
        self._res_anti: List[bool] = []
        self.zone_ids = dict(cat.zone_ids)
        self.ct_ids = dict(cat.ct_ids)
        self._frozen = False

    def add_input(self, inp: ScheduleInput) -> None:
        self.add_nodes(inp.existing_nodes)

    def add_nodes(self, existing: Sequence[ExistingNode]) -> None:
        """Register wrappers directly — the sweep path seeds the cache
        from the shared snapshot list (ScheduleInput.exist_base) instead
        of per-input node sets, so union row i == snapshot row i."""
        assert not self._frozen
        for en in existing:
            node = en.node
            if id(node) in self._index:
                continue
            # identity-keyed row lookup, never iterated: row order is
            # add_nodes() call order (the shared snapshot's), so
            # addresses cannot order anything
            self._index[id(node)] = len(self._nodes)  # kt-lint: disable=nondeterminism-source
            self._nodes.append(node)
            self._wrappers.append(en)
            self._res_anti.append(_has_required_anti(en.pods))

    def freeze(self) -> None:
        if self._frozen:
            return
        self._frozen = True
        nodes = self._nodes
        self.vocab = _Vocab()
        keys = sorted({k for n in nodes for k in n.labels})
        self.matrices = _label_matrix(
            self.vocab, keys, [n.labels for n in nodes])
        self.usable = np.array(
            [not n.meta.deleting and n.ready for n in nodes], dtype=bool)
        for n in nodes:
            z = n.labels.get(wellknown.ZONE_LABEL)
            if z is not None:
                self.zone_ids.setdefault(z, len(self.zone_ids))
            t = n.labels.get(wellknown.CAPACITY_TYPE_LABEL)
            if t is not None:
                self.ct_ids.setdefault(t, len(self.ct_ids))
        self.zone = np.array(
            [self.zone_ids.get(n.labels.get(wellknown.ZONE_LABEL), -1)
             for n in nodes], dtype=np.int32)
        self.ct = np.array(
            [self.ct_ids.get(n.labels.get(wellknown.CAPACITY_TYPE_LABEL), -1)
             for n in nodes], dtype=np.int32)
        self.res_anti = np.array(self._res_anti, dtype=bool)
        # nodes with taints are rare; only they need the per-group loop
        self._tainted = [i for i, n in enumerate(nodes) if n.taints]
        self._group_ok: Dict[int, np.ndarray] = {}
        # available-capacity rows keyed by the WRAPPER seen at add time:
        # sims that share ExistingNode objects (the sweep's common case)
        # skip the 2k-row nested-list conversion; a sim carrying a fresh
        # wrapper for a known node gets its row rebuilt from its own
        # values, so a differing snapshot can never be silently shadowed
        self._avail = np.array([en.available.v for en in self._wrappers],
                               dtype=np.float32).reshape(len(nodes), R)
        self._wrapper_id = [id(en) for en in self._wrappers]

    def exist_remaining(self, existing: Sequence[ExistingNode],
                        rows: np.ndarray) -> np.ndarray:
        out = self._avail[rows]
        wid = self._wrapper_id
        for j, en in enumerate(existing):
            if id(en) != wid[rows[j]]:
                out[j] = en.available.v
        return out

    def res_anti_any(self, existing: Sequence[ExistingNode],
                     rows: np.ndarray) -> bool:
        """Whether any resident pod carries required anti-affinity — with
        the same wrapper-divergence guard as exist_remaining: a sim whose
        fresh wrapper carries a different resident set than the snapshot
        must be judged on ITS pods, not the cached flag."""
        wid = self._wrapper_id
        for j, en in enumerate(existing):
            if id(en) == wid[rows[j]]:
                if self.res_anti[rows[j]]:
                    return True
            elif _has_required_anti(en.pods):
                return True
        return False

    def rows(self, existing: Sequence[ExistingNode]) -> np.ndarray:
        """Union row index per ExistingNode (identity-keyed on .node)."""
        # identity-keyed lookup in caller-supplied order — see add_nodes
        return np.fromiter((self._index[id(en.node)] for en in existing),  # kt-lint: disable=nondeterminism-source
                           dtype=np.int64, count=len(existing))

    def group_ok(self, rep: Pod) -> np.ndarray:
        """Usable ∧ requirements-matched ∧ taints-tolerated over the
        union, cached per pod equivalence class."""
        gid = rep.scheduling_group_id()
        ok = self._group_ok.get(gid)
        if ok is None:
            ok = _eval_requirements(rep.requirements, self.vocab,
                                    self.matrices, len(self._nodes))
            ok = ok & self.usable
            for i in self._tainted:
                if ok[i] and not tolerates_all(self._nodes[i].taints,
                                               rep.tolerations):
                    ok[i] = False
            self._group_ok[gid] = ok
        return ok


class SweepTopologyTables:
    """Per-class topology tables for the consolidation sweep's HEAVY lane.

    The sweep's whole point is that per-simulation host work stays O(pods),
    never O(cluster): the shared snapshot's per-node facts upload once.
    Topology-constrained pods used to hole out to the generic batched path
    (paying the per-sim [E,*] encode the sweep exists to kill); this class
    precomputes, ONCE per sweep, everything their kernel tensors need —
    per-(selector, key) per-node matching-resident counts, per-class
    hostname clamps, eligible-domain masks — so a simulation's dynamic
    tensors (dbase/dcap after ITS exclusions) are O(X) arithmetic.

    Supported per-class shapes mirror the kernel's dynamic machinery
    (_solve_ffd_impl's heavy branch): at most ONE dynamic self-matching
    zone/capacity-type term (DoNotSchedule spread with maxSkew/minDomains,
    or required anti-affinity), plus self-matching hostname spread/anti as
    ncap + per-node clamps.  Everything else raises `Unsupported` and the
    simulation stays a hole for the generic path: non-self-match selectors
    (static allowed-set math), required co-location (seed pin needs
    per-sim state), preferences (host relaxation ladder).
    """

    def __init__(self, base: Sequence, zone_arr: np.ndarray,
                 ct_arr: np.ndarray, zone_ids: Dict[str, int],
                 ct_ids: Dict[str, int]):
        self.base = base
        self.zone_arr = zone_arr          # [E] zone id per snapshot node
        self.ct_arr = ct_arr              # [E] ct id per snapshot node
        self.zone_ids = zone_ids
        self.ct_ids = ct_ids
        self.D = max(len(zone_ids), len(ct_ids), 1)
        self.E = len(base)
        self._counts: Dict[tuple, np.ndarray] = {}
        self._class_topo: Dict[int, dict] = {}
        # resident required-anti index (ONE scan): (key, selector) →
        # [E] bool, node holds a resident whose required anti-affinity
        # carries that (key, selector).  Classes matched by a selector
        # get those nodes'/domains' placements blocked (the oracle's
        # symmetric_anti_blocked_domains, sweep-shaped) — without this,
        # one anti-affinity pod anywhere in the cluster would disable
        # the whole sweep.
        self._res_anti: Dict[tuple, np.ndarray] = {}
        for ei, en in enumerate(base):
            for p in en.pods:
                for t in p.pod_affinities:
                    if not (t.required and t.anti):
                        continue
                    k = (t.topology_key,
                         tuple(sorted(t.label_selector.items())))
                    flags = self._res_anti.get(k)
                    if flags is None:
                        flags = np.zeros(self.E, dtype=bool)
                        self._res_anti[k] = flags
                    flags[ei] = True

    def counts_per_node(self, selector: Dict[str, str]) -> np.ndarray:
        """Matching resident pods per snapshot node ([E] i32), cached per
        selector — the one O(cluster) scan, paid once per distinct
        selector per sweep."""
        key = tuple(sorted(selector.items()))
        out = self._counts.get(key)
        if out is None:
            out = np.zeros(self.E, dtype=np.int32)
            for ei, en in enumerate(self.base):
                out[ei] = sum(1 for p in en.pods
                              if _matches(selector, p.meta.labels))
            self._counts[key] = out
        return out

    def _dom_total(self, counts: np.ndarray, dom_arr: np.ndarray) -> np.ndarray:
        total = np.zeros(self.D, dtype=np.int32)
        valid = dom_arr >= 0
        np.add.at(total, dom_arr[valid], counts[valid])
        return total

    def class_topo(self, rep: Pod) -> dict:
        """Class-level topology info (cached): static parts of the kernel
        tensors plus the per-node count arrays the per-sim math needs.
        Raises Unsupported for shapes the sweep can't express."""
        gid = rep.scheduling_group_id()
        info = self._class_topo.get(gid)
        if info is not None:
            if isinstance(info, Unsupported):
                raise info
            return info
        try:
            info = self._build_class_topo(rep)
        except Unsupported as e:
            self._class_topo[gid] = e
            raise
        self._class_topo[gid] = info
        return info

    def _build_class_topo(self, rep: Pod) -> dict:
        my = rep.meta.labels
        ncap = BIG
        hostcap = np.full(self.E, BIG, dtype=np.int32)
        dyn = None  # (key, dsel, anti flag, selector, skew, mindom)

        def set_dyn(key, anti, sel, skew=BIG, mindom=0):
            nonlocal dyn
            if dyn is not None:
                raise Unsupported("multiple dynamic topology terms")
            dsel = 1 if key == wellknown.ZONE_LABEL else 2
            dyn = dict(key=key, dsel=dsel, anti=anti, selector=dict(sel),
                       skew=skew, mindom=mindom)

        for c in rep.topology_spread:
            if c.when_unsatisfiable != "DoNotSchedule":
                continue  # best-effort never blocks (encoder parity)
            key = c.topology_key
            if key not in _TOPO_KEYS:
                raise Unsupported(f"spread topology key {key}")
            if not _matches(c.label_selector, my):
                raise Unsupported("non-self-match spread in sweep")
            counts = self.counts_per_node(c.label_selector)
            if key == wellknown.HOSTNAME_LABEL:
                ncap = min(ncap, c.max_skew)
                hostcap = np.minimum(hostcap,
                                     np.maximum(c.max_skew - counts, 0))
            else:
                set_dyn(key, False, c.label_selector, skew=c.max_skew,
                        mindom=c.min_domains or 0)
        for t in rep.pod_affinities:
            if not t.required:
                continue
            if not t.anti:
                raise Unsupported("required co-location in sweep")
            key = t.topology_key
            if key not in _TOPO_KEYS:
                raise Unsupported(f"affinity topology key {key}")
            if not _matches(t.label_selector, my):
                raise Unsupported("non-self-match anti in sweep")
            counts = self.counts_per_node(t.label_selector)
            if key == wellknown.HOSTNAME_LABEL:
                ncap = min(ncap, 1)
                hostcap = np.minimum(hostcap, np.maximum(1 - counts, 0))
            else:
                set_dyn(key, True, t.label_selector)

        # symmetric anti: resident required-anti terms whose selector
        # matches THIS class block the holding node (hostname key) or the
        # holding node's domain (zone/ct key) — per-sim, because an
        # excluded node's residents stop blocking
        sym_key = None
        sym_flags = None
        for (key, sel_t), flags in self._res_anti.items():
            if not _matches(dict(sel_t), my):
                continue
            if key == wellknown.HOSTNAME_LABEL:
                hostcap = np.where(flags, 0, hostcap).astype(np.int32)
            elif key in _DOM_KEYS:
                if sym_key is not None and sym_key != key:
                    raise Unsupported(
                        "symmetric anti on two domain keys")
                sym_key = key
                sym_flags = (flags if sym_flags is None
                             else (sym_flags | flags))
            else:
                raise Unsupported(f"symmetric anti-affinity on {key}")
        if sym_key is not None:
            if dyn is None:
                # borrow the dynamic slot: dcap 0 on blocked domains,
                # skew unbounded — pure domain blocking
                set_dyn(sym_key, True, {})
                dyn["counts"] = np.zeros(self.E, dtype=np.int32)
                dyn["sym_only"] = True
            elif dyn["key"] != sym_key:
                raise Unsupported(
                    "symmetric anti key differs from dynamic key")
            dyn["sym_flags"] = sym_flags

        delig = np.zeros(self.D, dtype=bool)
        dsel = 0
        if dyn is not None:
            dsel = dyn["dsel"]
            ids = (self.zone_ids if dyn["dsel"] == 1 else self.ct_ids)
            req = rep.requirements.get(dyn["key"])
            for d, i in ids.items():
                if req is None or req.matches(d):
                    delig[i] = True
            dom_arr = self.zone_arr if dyn["dsel"] == 1 else self.ct_arr
            if "counts" not in dyn:
                dyn["counts"] = self.counts_per_node(dyn["selector"])
            dyn["dom_total"] = self._dom_total(dyn["counts"], dom_arr)
            dyn["dom_arr"] = dom_arr
            if dyn.get("sym_flags") is not None:
                dyn["sym_idx"] = np.nonzero(dyn["sym_flags"])[0]
        return dict(ncap=ncap, hostcap=hostcap, dyn=dyn, dsel=dsel,
                    delig=delig)

    def sim_tensors(self, info: dict, excl: Sequence[int]):
        """(dbase, dcap) for ONE simulation: the class totals minus the
        excluded nodes' contributions, plus symmetric-anti domain
        blocking over the KEPT flagged nodes — O(X + flagged), never
        O(E)."""
        dbase = np.zeros(self.D, dtype=np.int32)
        dcap = np.full(self.D, BIG, dtype=np.int32)
        dyn = info["dyn"]
        if dyn is None:
            return dbase, dcap
        after = dyn["dom_total"].copy()
        for e in excl:
            if 0 <= e < self.E:
                d = dyn["dom_arr"][e]
                if d >= 0:
                    after[d] -= dyn["counts"][e]
        if dyn.get("sym_only"):
            pass  # pure symmetric blocking: no own-term counts
        elif dyn["anti"]:
            # at most one matching pod per domain (encoder parity:
            # dcap = 1 - counts, dbase untouched)
            dcap = np.maximum(1 - after, 0).astype(np.int32)
        else:
            dbase = after
        if dyn.get("sym_flags") is not None:
            excl_set = set(int(e) for e in excl)
            for e in dyn["sym_idx"]:
                if int(e) not in excl_set:
                    d = dyn["dom_arr"][e]
                    if d >= 0:
                        dcap[d] = 0
        return dbase, dcap


class _TopologyEncoder:
    """Classifies each group's spread / (anti-)affinity constraints and
    produces the kernel's topology tensors; raises `Unsupported` for shapes
    the tensor encoding can't express — custom topology keys, hostname
    co-location seeding, and selectors that couple pending groups (their
    counts would change with other groups' placements mid-solve) — so the
    caller falls back to the CPU oracle.  Required pod affinity on
    zone/capacity-type encodes as static domain restrictions (populated
    domains, or a host-side seed pin for the self-selector first-placement
    case). Mirrors scheduling/topology.py; reference surface:
    website/content/en/preview/concepts/scheduling.md:209-417.
    """

    def __init__(self, inp: ScheduleInput, cat: "CatalogEncoding",
                 groups: List[List[Pod]], split_mode: bool = False,
                 shared: Optional[SharedExistEncoding] = None,
                 shared_rows: Optional[np.ndarray] = None):
        # split mode: groups that raise Unsupported become host-side
        # residue solved AFTER the device solve, so the victim-side
        # coupling check (another pending group's anti matching this one)
        # can be skipped — the anti's OWNER always lands in the residue
        # (its own selector-couples-pending check fires), and the oracle
        # registers the device placements before placing it, which
        # enforces the symmetry.
        self.split_mode = split_mode
        self.cat = cat  # for the seed-domain pick (column prices)
        self.dense_layout = cat.layout == "dense"
        # seeding the tracker walks every resident pod — skip it entirely
        # when no pending pod carries a constraint and no resident pod
        # carries required anti-affinity (the only way existing state can
        # constrain unconstrained pods). This keeps consolidation's batched
        # per-candidate encodes O(pods), not O(cluster).
        has_constraints = any(
            g[0].topology_spread or g[0].pod_affinities for g in groups)
        if shared is not None:
            self.active = has_constraints or shared.res_anti_any(
                inp.existing_nodes, shared_rows)
        else:
            self.active = has_constraints or any(
                _has_required_anti(en.pods) for en in inp.existing_nodes)
        self.tracker = TopologyTracker()
        if self.active:
            for en in inp.existing_nodes:
                domains = node_domains_for(en.node.labels, en.node.name)
                for key, dom in domains.items():
                    self.tracker.observe_domains(key, {dom})
                for pod in en.pods:
                    self.tracker.register(pod, domains)
            self.tracker.observe_domains(
                wellknown.ZONE_LABEL, {c.zone for c in cat.columns})
            self.tracker.observe_domains(
                wellknown.CAPACITY_TYPE_LABEL,
                {c.capacity_type for c in cat.columns})
        # domain vocab: catalog ids first (stable across calls), existing-node
        # domains appended per call (union-wide when a batch cache is shared,
        # so every simulation in the batch agrees on D and the jit cache
        # sees one bucketed domain shape)
        self.existing = inp.existing_nodes
        if shared is not None:
            self.zone_ids = shared.zone_ids
            self.ct_ids = shared.ct_ids
            self.exist_zone = shared.zone[shared_rows]
            self.exist_ct = shared.ct[shared_rows]
        else:
            self.zone_ids = dict(cat.zone_ids)
            self.ct_ids = dict(cat.ct_ids)
            for en in inp.existing_nodes:
                z = en.node.labels.get(wellknown.ZONE_LABEL)
                if z is not None:
                    self.zone_ids.setdefault(z, len(self.zone_ids))
                t = en.node.labels.get(wellknown.CAPACITY_TYPE_LABEL)
                if t is not None:
                    self.ct_ids.setdefault(t, len(self.ct_ids))
            self.exist_zone = np.array(
                [self.zone_ids.get(en.node.labels.get(wellknown.ZONE_LABEL), -1)
                 for en in self.existing], dtype=np.int32).reshape(len(self.existing))
            self.exist_ct = np.array(
                [self.ct_ids.get(en.node.labels.get(wellknown.CAPACITY_TYPE_LABEL), -1)
                 for en in self.existing], dtype=np.int32).reshape(len(self.existing))
        self.group_labels = [g[0].meta.labels for g in groups]
        # gang units (ISSUE 15): per-group gang specs + the gang-name →
        # group-index map for the heterogeneous-gang check (two pod
        # classes sharing one gang name would break gang-level
        # atomicity in the per-group kernel — the oracle handles them)
        self.gangs = {}
        self._gang_groups: Dict[str, list] = {}
        for i, g in enumerate(groups):
            sp = gang_of(g[0])
            if sp is not None:
                self.gangs[i] = sp
                self._gang_groups.setdefault(sp.name, []).append(i)
        # gang names with members already BOUND on live nodes: their
        # pending remainder is a RESIDUAL placement (a recreated member
        # of a running gang) — completeness counts the bound members
        # and the ranks must join their domain, which the per-group
        # kernel unit can't express; _encode_gang routes these to the
        # oracle.  Only scanned when the problem has gangs at all.
        self._bound_gangs: set = set()
        if self.gangs:
            for en in self.existing:
                for p in en.pods:
                    bsp = gang_of(p)
                    if bsp is not None:
                        self._bound_gangs.add(bsp.name)
        self.D = max(len(self.zone_ids), len(self.ct_ids), 1)
        self._sel_cache: Dict[tuple, set] = {}
        # pending groups' required anti terms (for the symmetry coupling check)
        self.pending_anti: List[tuple] = [
            (i, dict(t.label_selector))
            for i, g in enumerate(groups)
            for t in g[0].pod_affinities if t.required and t.anti
        ]

    def _matching_groups(self, selector: Dict[str, str]) -> set:
        key = tuple(sorted(selector.items()))
        out = self._sel_cache.get(key)
        if out is None:
            out = {i for i, lbls in enumerate(self.group_labels)
                   if _matches(selector, lbls)}
            self._sel_cache[key] = out
        return out

    def _dom_ids(self, key: str) -> Dict[str, int]:
        return self.zone_ids if key == wellknown.ZONE_LABEL else self.ct_ids

    def _seed_domain(self, rep: Pod, key: str,
                     already_allowed: Optional[set]) -> Optional[str]:
        """The domain a self-matching required-affinity group seeds when
        no matching pod exists anywhere.  The oracle seeds wherever its
        first FFD placement lands — existing nodes first, then the
        cheapest new node — so prefer the domain with the most free
        existing CPU, tiebreak by cheapest compatible catalog column,
        then lexicographic for determinism.  A wrong pick can strand the
        group (capacity missing in the pinned domain); the solver's
        rescue path re-seeds those pods through the oracle."""
        ids = self._dom_ids(key)
        cand = set(ids)
        if already_allowed is not None:
            cand &= {d for d, i in ids.items() if i in already_allowed}
        elig = self.tracker.eligible_domains(rep, key)
        if elig:
            cand &= set(elig)
        if not cand:
            return None
        cap_by = {d: 0.0 for d in sorted(cand)}
        for en in self.existing:
            d = en.node.labels.get(key)
            if d in cap_by:
                cap_by[d] += max(float(en.available.get("cpu") or 0.0), 0.0)
        price_by = {d: float("inf") for d in sorted(cand)}
        gmask, _ = group_column_mask(self.cat, rep)
        for o_idx in np.nonzero(gmask)[0]:
            col = self.cat.columns[o_idx]
            d = (col.zone if key == wellknown.ZONE_LABEL
                 else col.capacity_type)
            if d in price_by and col.price < price_by[d]:
                price_by[d] = col.price
        return sorted(cand, key=lambda d: (-cap_by[d], price_by[d], d))[0]

    def _static_gmin(self, rep: Pod, key: str, counts, mindom) -> int:
        eligible = self.tracker.eligible_domains(rep, key)
        if not eligible:
            return 0
        gmin = min(counts.get(d, 0) for d in eligible)
        if mindom is not None:
            populated = sum(1 for d in eligible if counts.get(d, 0) > 0)
            if populated < mindom:
                gmin = 0
        return gmin

    def _encode_gang(self, gi: int, rep: Pod, spec) -> dict:
        """Gang-unit tensors (ISSUE 15): dsel names the adjacency axis,
        dbase the lexicographic domain trial rank (the SAME order the
        oracle's trial loop walks — scheduling.types.gang_trial_order),
        delig the domains the gang may try.  Everything else stays the
        inactive-encoder constants: the kernel's gang branch owns all
        fill-time restriction, so no static mask narrowing happens
        here.  Shapes the tensor encoding can't express atomically —
        gangs combined with other topology constraints, soft terms, or
        a gang spanning several pod classes — raise Unsupported and the
        gang rides the residue to the (gang-aware) oracle."""
        if rep.topology_spread or rep.pod_affinities or rep.preferences:
            raise Unsupported(
                "gang combined with topology/soft constraints")
        if len(self._gang_groups.get(spec.name, ())) > 1:
            raise Unsupported("gang spans multiple pod classes")
        if spec.name in self._bound_gangs:
            raise Unsupported("gang has bound members")
        E = len(self.existing)
        out = dict(
            ncap=BIG, ecap=np.full(E, BIG, dtype=np.int32), dsel=0,
            dbase=np.zeros(self.D, dtype=np.int32),
            dcap=np.full(self.D, BIG, dtype=np.int32), skew=BIG,
            mindom=0, delig=np.zeros(self.D, dtype=bool),
            allowed={k: None for k in _DOM_KEYS},
            requires={k: False for k in _DOM_KEYS},
            whole_node=False, gang=True)
        if spec.domain_key is None:
            # domain-free gang: one global trial domain (the kernel
            # maps every column/node to domain 0 when dsel == 0)
            out["delig"][0] = True
            return out
        if self.dense_layout:
            # the gang branch reads a column's domain from its grid
            # slot (ffd zc_dom), same invariant as dynamic spread
            raise Unsupported("gang adjacency on a dense catalog layout")
        out["dsel"] = 1 if spec.domain_key == wellknown.ZONE_LABEL else 2
        ids = self._dom_ids(spec.domain_key)
        req = rep.requirements.get(spec.domain_key)
        for pos, d in enumerate(gang_trial_order(ids)):
            i = ids[d]
            out["dbase"][i] = pos
            if req is None or req.matches(d):
                out["delig"][i] = True
        # no eligible domain ⇒ the kernel strands the gang whole
        # (GangDomainExhausted) — exactly the oracle's empty-trial-list
        # verdict, so no Unsupported here
        return out

    def encode_group(self, gi: int, rep: Pod) -> dict:
        spec = self.gangs.get(gi)
        if spec is not None:
            # gangs bypass the inactive-encoder fast path: their domain
            # tensors are needed even when no spread/affinity is active
            return self._encode_gang(gi, rep, spec)
        E = len(self.existing)
        if not self.active:
            return dict(
                ncap=BIG, ecap=np.full(E, BIG, dtype=np.int32), dsel=0,
                dbase=np.zeros(self.D, dtype=np.int32),
                dcap=np.full(self.D, BIG, dtype=np.int32), skew=BIG, mindom=0,
                delig=np.zeros(self.D, dtype=bool),
                allowed={k: None for k in _DOM_KEYS},
                requires={k: False for k in _DOM_KEYS},
                whole_node=False)
        ncap = BIG
        ecap = np.full(E, BIG, dtype=np.int32)
        whole_node = False
        allowed: Dict[str, Optional[set]] = {k: None for k in _DOM_KEYS}
        requires: Dict[str, bool] = {k: False for k in _DOM_KEYS}
        dyn_key: Optional[str] = None
        dbase = np.zeros(self.D, dtype=np.int32)
        dcap = np.full(self.D, BIG, dtype=np.int32)
        skew = BIG
        mindom = 0
        my = rep.meta.labels

        def clamp_hosts(cap_of_host):
            for ei, en in enumerate(self.existing):
                c = cap_of_host(en.node.name)
                if c < ecap[ei]:
                    ecap[ei] = max(int(c), 0)

        def restrict(key, dom_names: set):
            ids = self._dom_ids(key)
            sid = {ids[d] for d in dom_names if d in ids}
            allowed[key] = sid if allowed[key] is None else (allowed[key] & sid)

        for c in rep.topology_spread:
            if c.when_unsatisfiable != "DoNotSchedule":
                continue  # ScheduleAnyway is best-effort, never blocks
            key = c.topology_key
            if key not in _TOPO_KEYS:
                raise Unsupported(f"spread topology key {key}")
            if self._matching_groups(c.label_selector) - {gi}:
                raise Unsupported("spread selector couples pending groups")
            self_match = _matches(c.label_selector, my)
            counts = self.tracker.counts_for(key, c.label_selector)
            if key == wellknown.HOSTNAME_LABEL:
                # a fresh hostname domain is always available, so the global
                # minimum is 0 and maxSkew is a per-node ceiling (slightly
                # conservative when every candidate node holds matching pods)
                if self_match:
                    ncap = min(ncap, c.max_skew)
                    clamp_hosts(lambda h: c.max_skew - counts.get(h, 0))
                else:
                    clamp_hosts(
                        lambda h: BIG if counts.get(h, 0) + 1 <= c.max_skew else 0)
            elif self_match:
                if dyn_key is not None and dyn_key != key:
                    raise Unsupported("two dynamic topology keys on one pod")
                if skew != BIG:
                    raise Unsupported("multiple dynamic spread constraints")
                dyn_key = key
                skew = c.max_skew
                mindom = c.min_domains or 0
                ids = self._dom_ids(key)
                for d, n in counts.items():
                    if d in ids:
                        dbase[ids[d]] = n
            else:
                # counts never change with this group's placements → the
                # allowed-domain set is static; fold it into the masks
                gmin = self._static_gmin(rep, key, counts, c.min_domains)
                ok = {d for d in self._dom_ids(key)
                      if counts.get(d, 0) + 1 - gmin <= c.max_skew}
                restrict(key, ok)
                requires[key] = True

        for t in rep.pod_affinities:
            if not t.required:
                continue  # preferred terms are not consumed (oracle parity)
            key = t.topology_key
            if key not in _TOPO_KEYS:
                raise Unsupported(f"affinity topology key {key}")
            if self._matching_groups(t.label_selector) - {gi}:
                raise Unsupported("affinity selector couples pending groups")
            self_match = _matches(t.label_selector, my)
            counts = self.tracker.counts_for(key, t.label_selector)
            if not t.anti:
                # required CO-LOCATION affinity (oracle:
                # topology.affinity_allowed_domains) — three shapes:
                #   populated domains exist → each member restricted to
                #     them (static: counts can't shrink mid-solve);
                #   none populated + self-matching → the group seeds ONE
                #     domain; the oracle seeds wherever its first FFD
                #     placement lands, the device path pre-pins the
                #     domain host-side (most free existing capacity,
                #     then cheapest compatible column);
                #   none populated + not self-matching → nothing is
                #     allowed (kube semantics), encoded as an empty
                #     domain restriction.
                populated = {d for d, n in counts.items() if n > 0}
                if key == wellknown.HOSTNAME_LABEL:
                    if populated:
                        # members must share a host with a match; fresh
                        # nodes have none, so new-node placement is off
                        ncap = 0
                        clamp_hosts(
                            lambda h: BIG if h in populated else 0)
                    elif self_match:
                        # all members on ONE node, fresh or existing:
                        # "exactly one node" is not a column-model
                        # concept, but "every candidate must hold the
                        # WHOLE group" is — flag it for the caller,
                        # which owns the column/row capacity math (the
                        # group count lives there).  Encode-time
                        # eligibility is against ORIGINAL capacity, so
                        # the fill can still split the group when an
                        # earlier group consumed an eligible node —
                        # the post-solve whole-node repair strands such
                        # groups atomically and the rescue hands them
                        # to the oracle (its seed-then-strand is the
                        # reference semantics).
                        whole_node = True
                    else:
                        # no populated host and the selector does NOT
                        # match the group itself: nothing satisfies the
                        # required term (kube semantics — same verdict
                        # as the zone/ct branch's restrict(key, set()))
                        ncap = 0
                        clamp_hosts(lambda h: 0)
                elif populated:
                    restrict(key, populated)
                    requires[key] = True
                elif self_match:
                    pin = self._seed_domain(rep, key, allowed[key])
                    restrict(key, {pin} if pin is not None else set())
                    requires[key] = True
                else:
                    restrict(key, set())
                    requires[key] = True
                continue
            if key == wellknown.HOSTNAME_LABEL:
                if self_match:
                    ncap = min(ncap, 1)
                    clamp_hosts(lambda h: 1 - counts.get(h, 0))
                else:
                    clamp_hosts(lambda h: 0 if counts.get(h, 0) else BIG)
            elif self_match:
                if dyn_key is not None and dyn_key != key:
                    raise Unsupported("two dynamic topology keys on one pod")
                dyn_key = key
                ids = self._dom_ids(key)
                for d, i in ids.items():
                    dcap[i] = min(int(dcap[i]), max(0, 1 - counts.get(d, 0)))
            else:
                blocked = {d for d, n in counts.items() if n > 0}
                restrict(key, set(self._dom_ids(key)) - blocked)
                requires[key] = True

        # symmetry: already-placed pods' required anti-affinity blocks this
        # group (oracle `_affinity_ok` tail); label-absent nodes pass
        for key in self.tracker.anti_topology_keys():
            blocked = self.tracker.symmetric_anti_blocked_domains(rep, key)
            if not blocked:
                continue
            if key == wellknown.HOSTNAME_LABEL:
                clamp_hosts(lambda h: 0 if h in blocked else BIG)
            elif key in _DOM_KEYS:
                if dyn_key == key:
                    ids = self._dom_ids(key)
                    for d in sorted(blocked):
                        if d in ids:
                            dcap[ids[d]] = 0
                else:
                    restrict(key, set(self._dom_ids(key)) - blocked)
            else:
                raise Unsupported(f"symmetric anti-affinity on {key}")
        # pending groups' anti terms matching this group couple dynamically
        if not self.split_mode:
            for gj, sel in self.pending_anti:
                if gj != gi and _matches(sel, my):
                    raise Unsupported("another pending group's anti-affinity "
                                      "matches this group")

        dsel = 0
        delig = np.zeros(self.D, dtype=bool)
        if dyn_key is not None:
            if self.dense_layout:
                # the kernel's heavy branch reads a column's domain from
                # its slot index (ffd.py zc_dom = col_dom[:zc], valid only
                # for the fixed-stride grid); the dense fallback breaks
                # that invariant, so domain-spread groups go to the oracle
                raise Unsupported(
                    "domain spread on a dense catalog layout")
            dsel = 1 if dyn_key == wellknown.ZONE_LABEL else 2
            ids = self._dom_ids(dyn_key)
            for d in self.tracker.eligible_domains(rep, dyn_key):
                if d in ids:
                    delig[ids[d]] = True
            if allowed[dyn_key] is not None:
                # statically-blocked domains stay in the skew minimum but
                # can't take placements
                for d, i in ids.items():
                    if i not in allowed[dyn_key]:
                        dcap[i] = 0
                allowed[dyn_key] = None
        if whole_node and dsel > 0:
            # the kernel's ALL-or-nothing fill lives in the light branch;
            # the heavy (domain-partitioned) branch's per-domain fills
            # would split the group and strand it wholesale — the host
            # oracle handles both constraints coherently instead
            raise Unsupported(
                "whole-node co-location combined with dynamic spread")
        return dict(ncap=ncap, ecap=ecap, dsel=dsel, dbase=dbase, dcap=dcap,
                    skew=skew, mindom=mindom, delig=delig,
                    allowed=allowed, requires=requires,
                    whole_node=whole_node)


def _np_fit_count(avail: np.ndarray, req: np.ndarray) -> np.ndarray:
    """Host mirror of the kernel's _fit_count (ffd.py:60): how many pods
    of per-pod request `req` [R] fit in `avail` [..., R].  Same EPS so a
    host-side whole-group-fit verdict never disagrees with the device
    fill."""
    safe = np.where(req > 0, req, 1.0)
    counts = np.floor((avail + EPS) / safe)
    counts = np.where(req > 0, counts, float(2 ** 30))
    return np.clip(counts.min(axis=-1), 0, 2 ** 30).astype(np.int64)


def group_column_mask(cat: "CatalogEncoding", rep: Pod):
    """Per-pod-class catalog column mask + per-pool merged requirements —
    a pure function of (catalog, pod class), shared by the per-problem
    encoder and the batched sweep path (which caches it per class across
    thousands of simulations). Dead grid combos (no available offering)
    are folded in via col_valid."""
    O = len(cat.columns)
    merged_per_pool: List[Optional[Requirements]] = []
    gmask = np.zeros(O, dtype=bool)
    for pidx, pool in enumerate(cat.pools):
        if not tolerates_all(pool.taints, rep.tolerations):
            merged_per_pool.append(None)
            continue
        template = cat.templates[pidx]
        if not template.compatible(rep.requirements):
            merged_per_pool.append(None)
            continue
        merged = template.intersection(rep.requirements)
        merged_per_pool.append(merged)
        sel = cat.pool_cols[pidx]
        if len(sel) == 0:
            continue
        # Split merged requirements three ways (oracle's open-world type
        # check, tensorized):
        #   column-provided key   → vectorized closed-world check
        #   template-provided key → already validated by the template ∩
        #                           pod intersection; the node itself
        #                           will carry the label
        #   neither               → satisfiable only by absence
        col_checked = Requirements()
        feasible = True
        for req_ in merged:
            if req_.key in cat.pool_provides[pidx]:
                col_checked.add(req_)
            elif template.get(req_.key) is not None:
                continue
            elif not req_.matches_absent():
                feasible = False
                break
        if not feasible:
            continue
        ok = _eval_requirements(col_checked, cat.vocab,
                                cat.pool_matrices[pidx], len(sel))
        gmask[sel[ok]] = True
    return gmask & cat.col_valid, merged_per_pool


def encode(inp: ScheduleInput, cat: Optional[CatalogEncoding] = None,
           split: bool = False,
           exist_shared: Optional[SharedExistEncoding] = None,
           groups: Optional[List[List[Pod]]] = None) -> EncodedProblem:
    """split=False: raise Unsupported on the first inexpressible group
    (caller falls back wholesale).  split=True: collect inexpressible
    groups into `.residue` and encode the rest — the solver runs the
    device kernel on the supported majority and hands only the residue to
    the host oracle (VERDICT r1 #4: a 50k-pod problem with one affinity
    pod must not abandon the device).  exist_shared: a frozen per-batch
    union cache of existing-node encodings (consolidation sweep — the
    per-simulation node work collapses to row gathers)."""
    cat = cat or encode_catalog(inp)
    if any(en.charge_pool is not None for en in inp.existing_nodes):
        # synthetic claim-nodes (split/rescue augment outputs) charge the
        # pool limit per placement — the kernel's existing-node fills
        # don't, so such inputs must stay on the host oracle
        raise Unsupported(
            "existing nodes with charge_pool need host-side limit "
            "accounting")
    pools = cat.pools
    vocab = cat.vocab
    columns = cat.columns
    col_matrices = cat.col_matrices
    if groups is None:
        groups = group_pods(inp.pods)

    O = len(columns)
    E = len(inp.existing_nodes)
    G = len(groups)

    shared_rows = (exist_shared.rows(inp.existing_nodes)
                   if exist_shared is not None else None)
    topo = _TopologyEncoder(inp, cat, groups, split_mode=split,
                            shared=exist_shared, shared_rows=shared_rows)
    D = topo.D

    if exist_shared is None:
        # existing-node labels (hostnames are per-node-unique) go into a
        # per-call vocab so node churn can't grow the cached catalog vocab
        exist_vocab = _Vocab()
        exist_keys = sorted({k for en in inp.existing_nodes for k in en.node.labels})
        exist_matrices = _label_matrix(
            exist_vocab, exist_keys, [en.node.labels for en in inp.existing_nodes])

    group_req = np.zeros((G, R), dtype=np.float32)
    group_count = np.zeros(G, dtype=np.int32)
    group_mask = np.zeros((G, O), dtype=bool)
    exist_cap = np.zeros((G, E), dtype=np.int32)
    group_ncap = np.zeros(G, dtype=np.int32)
    group_dsel = np.zeros(G, dtype=np.int32)
    group_dbase = np.zeros((G, D), dtype=np.int32)
    group_dcap = np.zeros((G, D), dtype=np.int32)
    group_skew = np.zeros(G, dtype=np.int32)
    group_mindom = np.zeros(G, dtype=np.int32)
    group_delig = np.zeros((G, D), dtype=bool)
    group_whole_node = np.zeros(G, dtype=bool)
    group_gang = np.zeros(G, dtype=bool)
    group_priority = np.zeros(G, dtype=np.int32)
    static_allowed: List[Dict[str, Optional[set]]] = []
    merged_reqs: List[List[Optional[Requirements]]] = []

    _avail_rows = [None]

    def exist_avail() -> np.ndarray:
        """[E, R] remaining capacity, built once on first use — the same
        rows the kernel's exist fill sees (shared snapshot when present),
        so the whole-node verdicts can't disagree with the fill."""
        if _avail_rows[0] is None:
            if exist_shared is not None:
                _avail_rows[0] = exist_shared.exist_remaining(
                    inp.existing_nodes, shared_rows)
            else:
                _avail_rows[0] = np.array(
                    [en.available.v for en in inp.existing_nodes],
                    dtype=np.float32).reshape(E, R)
        return _avail_rows[0]

    pool_col = cat.col_pool
    dom_arrays = {wellknown.ZONE_LABEL: (cat.col_zone, topo.exist_zone),
                  wellknown.CAPACITY_TYPE_LABEL: (cat.col_ct, topo.exist_ct)}

    residue: List[Tuple[List[Pod], str]] = []
    dropped: List[int] = []
    for gi, g in enumerate(groups):
        rep = g[0]
        group_req[gi] = np.array(effective_request(rep).v, dtype=np.float32)
        group_count[gi] = len(g)
        group_priority[gi] = priority_of(rep)
        try:
            t = topo.encode_group(gi, rep)
        except Unsupported as e:
            if not split:
                raise  # → oracle fallback for the whole batch
            residue.append((g, str(e)))
            dropped.append(gi)
            continue
        group_ncap[gi] = t["ncap"]
        group_dsel[gi] = t["dsel"]
        group_dbase[gi] = t["dbase"]
        group_dcap[gi] = t["dcap"]
        group_skew[gi] = t["skew"]
        group_mindom[gi] = t["mindom"]
        group_delig[gi] = t["delig"]
        group_whole_node[gi] = t["whole_node"]
        group_gang[gi] = t.get("gang", False)

        gmask, merged_per_pool = group_column_mask(cat, rep)
        # static topology domain restrictions → column mask
        for key, (col_ids, _) in dom_arrays.items():
            al = t["allowed"][key]
            if al is not None:
                gmask = gmask & np.isin(col_ids, list(al))
        if t["whole_node"]:
            # hostname co-location seeding: every candidate column must
            # hold the WHOLE group (greedy fill then never splits it)
            gmask = gmask & (_np_fit_count(
                cat.col_alloc - cat.col_daemon,
                group_req[gi]) >= len(g))
        gang_incomplete = False
        if t.get("gang"):
            sp = topo.gangs[gi]
            if sp.size and len(g) != sp.size:
                # incomplete (or over-declared) gang: placement waits
                # for exactly the declared membership — zero the column
                # mask and the exist rows so the kernel strands the
                # gang WHOLE (decode emits GangIncomplete).  The oracle
                # applies the identical verdict, so parity holds.
                gmask = np.zeros_like(gmask)
                gang_incomplete = True
        static_allowed.append(t["allowed"])
        group_mask[gi] = gmask
        merged_reqs.append(merged_per_pool)

        if E:
            if exist_shared is not None:
                # union verdict cached per pod class; usable+taints folded in
                ok = exist_shared.group_ok(rep)[shared_rows]
            else:
                ok = exist_group_ok(rep, exist_vocab, exist_matrices,
                                    inp.existing_nodes)
            cap_row = np.where(ok, t["ecap"], 0).astype(np.int32)
            # static topology domain restrictions → per-node allowance
            for key, (_, ex_ids) in dom_arrays.items():
                al = t["allowed"][key]
                if al is not None:
                    ok_dom = np.isin(ex_ids, list(al))
                    if not t["requires"][key]:
                        ok_dom |= ex_ids < 0  # label-absent passes (symmetry)
                    cap_row = np.where(ok_dom, cap_row, 0)
            if t["whole_node"]:
                # all-or-nothing rows: only nodes whose remaining
                # capacity absorbs the full group stay eligible
                cap_row = np.where(
                    _np_fit_count(exist_avail(), group_req[gi]) >= len(g),
                    cap_row, 0)
            if gang_incomplete:
                cap_row = np.zeros_like(cap_row)
            exist_cap[gi] = cap_row

    if dropped:
        keep = np.ones(G, dtype=bool)
        keep[dropped] = False
        group_req = group_req[keep]
        group_count = group_count[keep]
        group_mask = group_mask[keep]
        exist_cap = exist_cap[keep]
        group_ncap = group_ncap[keep]
        group_dsel = group_dsel[keep]
        group_dbase = group_dbase[keep]
        group_dcap = group_dcap[keep]
        group_skew = group_skew[keep]
        group_mindom = group_mindom[keep]
        group_delig = group_delig[keep]
        group_whole_node = group_whole_node[keep]
        group_gang = group_gang[keep]
        group_priority = group_priority[keep]
        groups = [g for gi, g in enumerate(groups) if keep[gi]]
        # static_allowed / merged_reqs were only appended for kept groups

    exist_remaining = exist_avail()

    pool_limit = np.full((max(len(pools), 1), R), np.inf, dtype=np.float32)
    for pidx, pool in enumerate(pools):
        lim = inp.remaining_limits.get(pool.name)
        if lim is not None:
            pool_limit[pidx] = np.array(lim.v, dtype=np.float32)

    zone_values = [None] * len(topo.zone_ids)
    for z, i in topo.zone_ids.items():
        zone_values[i] = z
    ct_values = [None] * len(topo.ct_ids)
    for ct, i in topo.ct_ids.items():
        ct_values[i] = ct

    return EncodedProblem(
        group_req=group_req,
        group_count=group_count,
        group_mask=group_mask,
        exist_cap=exist_cap,
        exist_remaining=exist_remaining,
        col_alloc=cat.col_alloc,
        col_daemon=cat.col_daemon,
        col_price=cat.col_price,
        col_pool=pool_col,
        pool_limit=pool_limit,
        group_ncap=group_ncap,
        group_dsel=group_dsel,
        group_dbase=group_dbase,
        group_dcap=group_dcap,
        group_skew=group_skew,
        group_mindom=group_mindom,
        group_delig=group_delig,
        group_whole_node=group_whole_node,
        group_gang=group_gang,
        group_priority=group_priority,
        col_price_eff=cat.col_price_eff,
        col_zone=cat.col_zone,
        col_ct=cat.col_ct,
        exist_zone=topo.exist_zone,
        exist_ct=topo.exist_ct,
        zone_values=zone_values,
        ct_values=ct_values,
        n_domains=D,
        static_allowed=static_allowed,
        residue=residue,
        groups=groups,
        columns=columns,
        existing=list(inp.existing_nodes),
        pools=pools,
        merged_reqs=merged_reqs,
    )


def bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Round up to a fixed shape tier to avoid XLA recompiles
    (ragged-size discipline per SURVEY §7 hard-parts)."""
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** np.ceil(np.log2(max(n, 1))))
