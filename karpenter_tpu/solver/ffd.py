"""The jitted grouped-FFD kernel.

One `lax.scan` step consumes one pod equivalence class (already in FFD
order) and performs three vectorized fills, mirroring the oracle's
existing → in-flight → open-new cascade exactly but over whole groups:

  1. existing nodes: per-node pod capacity via elementwise floor-division,
     greedy prefix fill in node order (= sequential first-fit for identical
     pods)
  2. in-flight nodes: per-(node × column) capacity, max over each node's
     surviving columns, prefix fill; survivors' column masks AND-ed with the
     group's compatibility row
  3. open new nodes: best pods-per-node over feasible columns of the
     highest-priority compatible pool, ceil-divide to get node count,
     activate slots

Topology constraints (reference surface:
website/content/en/preview/concepts/scheduling.md:209-417) are encoded as
per-group tensors (SURVEY §7 step 5 — "zonal/hostname spread as per-domain
count tensors + penalty/feasibility masks"):

  - hostname spread / hostname anti-affinity → per-node caps (`group_ncap`,
    `exist_cap`): a fresh hostname domain always exists, so the global
    minimum is 0 and the per-node allowance is just maxSkew (resp. 1).
  - zone / capacity-type spread + anti-affinity → a domain axis D: the
    group's pod count is split into per-domain quotas by a closed-form
    water-fill against per-domain capacity, base counts, maxSkew and
    minDomains, then each fill above runs per-domain. Each touched node is
    pinned to its domain by narrowing its column mask (and recorded in
    `node_zone`/`node_ct` for the host-side claim narrowing, mirroring the
    oracle's `_resolve_topology` requirement pinning).

Only self-selecting constraints reach this kernel (the encoder falls back
to the CPU oracle for cross-group coupling), so all spread state is local
to one scan step — base counts are static and only the group's own
placements move them. Groups without a domain constraint take a `lax.cond`
branch identical to the original cascade, so the unconstrained hot path
pays nothing.

Everything is static-shaped (`G × E × O × N × D` padded to buckets by the
caller); control flow is masked arithmetic, no data-dependent branching —
the whole solve is one XLA program (SURVEY §7: compiler-friendly control
flow, no recompiles inside the latency budget).
"""

from __future__ import annotations

from collections import deque
from functools import partial

import jax
import jax.numpy as jnp

from karpenter_tpu.solver.explain import EPS, KERNEL_CONSTRAINTS

# placement-provenance aux (ISSUE 13): the kernel's per-group elimination
# counts use KERNEL_CONSTRAINTS order (fit, limit, topology, whole_node,
# slots) — explain.py is the enum owner, this is its device-side width
EXPLAIN_C = len(KERNEL_CONSTRAINTS)

# -- trace/compile telemetry ---------------------------------------------
# A jit cache miss re-executes the traced Python body (exactly once per
# miss under plain jit), and a retrace is the only event that can trigger
# an XLA compile — the persistent compilation cache can make a compile
# cheap, but never make a trace invisible.  Counting body executions
# therefore counts compiles conservatively: warmup() relies on this to
# assert "zero compiles on the first real solve after warm-up"
# (tests/test_solver_pipeline.py) without reaching into jax internals.
TRACE_COUNT = 0
TRACE_LOG: deque = deque(maxlen=256)  # recent trace shape keys (debug)


def _note_trace(**statics) -> None:
    global TRACE_COUNT
    TRACE_COUNT += 1
    TRACE_LOG.append(statics)
    # exported half of the counter (`karpenter_tpu_solver_retraces_total`
    # by padded shape bucket): the warm-up gates assert TRACE_COUNT
    # in-process, but a deployed operator only sees /metrics — a series
    # climbing post-warmup is a padding-bucket cliff the lattice missed.
    # Bucket cardinality is bounded by the warm-up lattice itself
    # (a few dozen programs per deployment).
    from karpenter_tpu.utils import metrics
    metrics.SOLVER_RETRACES.inc(bucket="G{G}_E{E}_O{O}_N{N}".format(
        G=statics.get("G", 0), E=statics.get("E", 0),
        O=statics.get("O", 0), N=statics.get("N", 0)))
# NOTE: no module-level jnp constants here — materializing a device array
# at import time eagerly initializes whatever backend the site default
# points at; importing the solver must never touch a device. The BIG
# sentinel lives in encode.py (the sole definition).


def _axmax(x: jnp.ndarray, axis_name, axis=None) -> jnp.ndarray:
    """Max over a (possibly mesh-sharded) axis: local max, then — under
    `shard_map` (axis_name set) — an explicit all-reduce-max over the
    mesh axis.  This is the kernel split's ONLY cross-device collective
    shape: every column-axis winner selection reduces locally on each
    device's catalog shard and combines via one `pmax`.  Max is exactly
    associative (no rounding), so the sharded value is bit-identical to
    the single-device reduction."""
    r = jnp.max(x, axis=axis)
    if axis_name is not None:
        r = jax.lax.pmax(r, axis_name)
    return r


def _axany(x: jnp.ndarray, axis_name) -> jnp.ndarray:
    """any() over a possibly-sharded axis (pmax over {0,1} — jax pmax
    rejects bools)."""
    r = jnp.any(x)
    if axis_name is not None:
        r = jax.lax.pmax(r.astype(jnp.int32), axis_name) > 0
    return r


def _fit_count(avail: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """How many pods of per-pod request `req` [R] fit in `avail` [..., R]."""
    safe = jnp.where(req > 0, req, 1.0)
    counts = jnp.floor((avail + EPS) / safe)
    counts = jnp.where(req > 0, counts, jnp.float32(2**30))
    c = jnp.min(counts, axis=-1)
    return jnp.clip(c, 0, 2**30).astype(jnp.int32)


def _prefix_fill(cap: jnp.ndarray, want: jnp.ndarray) -> jnp.ndarray:
    """Greedy fill in index order: take as much as each slot holds until
    `want` is exhausted — identical to sequential first-fit for
    interchangeable pods."""
    cum = jnp.cumsum(cap)
    before = cum - cap
    return jnp.clip(jnp.minimum(cap, want - before), 0, None)


def _atomic_fill(cap: jnp.ndarray, want: jnp.ndarray) -> jnp.ndarray:
    """ALL-or-nothing fill: the FIRST slot holding the entire `want`
    takes it; every other slot takes zero.  Whole-node co-location
    groups use this instead of _prefix_fill — a greedy partial take
    against fill-time capacity is exactly the silent split the required
    affinity forbids."""
    elig = cap >= want
    first = jnp.argmax(elig)
    idx = jnp.arange(cap.shape[0])
    return jnp.where((idx == first) & elig.any() & (want > 0),
                     want, 0).astype(cap.dtype)


def _water_fill(cnt, base, xmax, elig, skew, mindom):
    """Split `cnt` pods into per-domain quotas [D].

    Maximises total placement subject to DoNotSchedule spread semantics:
    final counts f_d = base_d + x_d with x_d ≤ xmax_d must satisfy
    max_eligible(f) - min_eligible(f) ≤ skew, where the minimum is treated
    as 0 while fewer than `mindom` domains are populated (the oracle's
    `spread_allowed_domains`, in closed form). Piecewise-linear in the
    water level L, so L* is found exactly by evaluating feasibility at the
    O(D) breakpoints — no data-dependent iteration.
    """
    D = base.shape[0]
    cnt_f = cnt.astype(jnp.float32)
    skew_f = skew.astype(jnp.float32)
    c = base.astype(jnp.float32)
    ub = jnp.where(elig, (base + xmax).astype(jnp.float32), c)

    def f_at(L):  # [K] → [K, D] final counts
        return jnp.clip(L[:, None], c[None, :], ub[None, :])

    def placed(L):  # [K]
        return (f_at(L) - c[None, :]).sum(-1)

    def minf(L):  # [K] skew floor (0 while under minDomains)
        f = f_at(L)
        m = jnp.where(elig[None, :], f, jnp.inf).min(-1)
        pop = (jnp.where(elig[None, :], f, 0.0) > 0.5).sum(-1)
        return jnp.where((mindom > 0) & (pop < mindom), 0.0, m)

    bps = jnp.sort(jnp.concatenate([c, ub]))                      # [2D]
    pl = placed(bps)
    # segment slope after each breakpoint = #domains actively filling
    slope = ((c[None, :] <= bps[:, None]) & (bps[:, None] < ub[None, :])
             & elig[None, :]).sum(-1)
    cands = jnp.concatenate([
        bps,
        minf(bps) + skew_f,                                       # skew crossings
        bps + (cnt_f - pl) / jnp.maximum(slope, 1),               # count crossing
    ])
    ok = ((cands <= minf(cands) + skew_f + EPS)
          & (placed(cands) <= cnt_f + EPS))
    L = jnp.floor(jnp.max(jnp.where(ok, cands, c.min() if D else 0.0)))
    x = (jnp.clip(L, c, ub) - c).astype(jnp.int32)
    # integral repair: flooring L strands < D pods; hand them to domains
    # whose bumped count still respects the skew floor
    leftover = jnp.maximum(cnt - x.sum(), 0)
    m = minf(L[None])[0]
    bumpable = elig & (c + x < ub) & (jnp.clip(L, c, ub) + 1.0 - m <= skew_f + EPS)
    x = x + _prefix_fill(bumpable.astype(jnp.int32), leftover)
    return jnp.minimum(x, cnt)


def _expand_packed_mask(m, O: int):
    """[G, ceil(O/8)] uint8 -> [G, O] bool: byte-gather along the column
    axis + bit shift (host side packs with np.packbits
    bitorder="little").  The shape assert is trace-time-free and turns a
    mask packed at the wrong column count (JAX would silently CLAMP the
    out-of-bounds byte gather) into an immediate error."""
    assert m.shape[-1] == (O + 7) // 8, (m.shape, O)
    o = jnp.arange(O, dtype=jnp.int32)
    return ((m[:, o // 8] >> (o % 8).astype(jnp.uint8)) & 1).astype(bool)


def _solve_ffd_impl(
    group_req: jnp.ndarray,       # [G, R]
    group_count: jnp.ndarray,     # [G]
    group_mask: jnp.ndarray,      # [G, O] bool
    exist_cap: jnp.ndarray,       # [G, E] i32 (0 = blocked)
    exist_remaining: jnp.ndarray, # [E, R]
    col_alloc: jnp.ndarray,       # [O, R]
    col_daemon: jnp.ndarray,      # [O, R]
    pt_alloc: jnp.ndarray,        # [PT, R] — allocatable per (pool,type)
    col_pool: jnp.ndarray,        # [O] i32
    pool_daemon: jnp.ndarray,     # [P, R]
    pool_limit: jnp.ndarray,      # [P, R]
    group_ncap: jnp.ndarray,      # [G] i32 per-new-node cap
    group_dsel: jnp.ndarray,      # [G] i32 0 none / 1 zone / 2 capacity-type
    group_dbase: jnp.ndarray,     # [G, D] i32 spread base counts
    group_dcap: jnp.ndarray,      # [G, D] i32 max additional per domain
    group_skew: jnp.ndarray,      # [G] i32
    group_mindom: jnp.ndarray,    # [G] i32 (0 = unset)
    group_delig: jnp.ndarray,     # [G, D] bool eligible domains for skew min
    group_whole: jnp.ndarray,     # [G] bool — whole-node co-location: fills
                                  # are ALL-or-nothing (encode restricts the
                                  # columns/rows to whole-group fits, but
                                  # fill-time capacity is dynamic — a
                                  # partial take would split the group)
    group_gang: jnp.ndarray,      # [G] bool — gang unit (ISSUE 15): the
                                  # group is an atomic K-NODE gang — it
                                  # commits only when every member fits
                                  # in ONE adjacency domain (dsel names
                                  # the axis; dbase carries the domain
                                  # trial RANK, not spread base counts);
                                  # otherwise nothing is placed.  Dead
                                  # unless the with_gang static is set.
    col_zone: jnp.ndarray,        # [O] i32
    col_ct: jnp.ndarray,          # [O] i32
    exist_zone: jnp.ndarray,      # [E] i32
    exist_ct: jnp.ndarray,        # [E] i32
    group_prio: jnp.ndarray = None,  # [G] i32 — effective priority per
                                  # group (ISSUE 16).  The BAND ORDER is
                                  # host-side (encode re-sorts groups
                                  # priority-desc before the scan, so
                                  # higher bands consume capacity
                                  # first); the kernel only WITNESSES:
                                  # with_priority appends a per-group
                                  # inversion bit — "this group placed
                                  # while an earlier (higher-priority)
                                  # group had already stranded" — the
                                  # decode-side gate for the
                                  # PriorityBandExhausted
                                  # reclassification.  Dead (may be
                                  # None) unless with_priority is set.
    seed_used: jnp.ndarray = None,     # [N, R] f32 — delta-seeded start:
                                  # the scan resumes from a previous
                                  # solve's prefix state (solver/delta.py)
                                  # instead of the all-zeros init.  The
                                  # caller guarantees the seeded slots are
                                  # a contiguous [0, A) block and that the
                                  # problem is topology-free (node_zone/ct
                                  # stay -1).
    seed_colmask: jnp.ndarray = None,  # [A_pad, O] bool — surviving-column
                                  # masks of the seeded slots (rows past
                                  # the active count are all-false, which
                                  # is exactly the unopened-slot state)
    seed_pool: jnp.ndarray = None,     # [N] i32
    seed_active: jnp.ndarray = None,   # [N] bool
    max_nodes: int = 1024,
    zc: int = 1,                  # grid stride: columns per (pool,type)
    with_topology: bool = True,   # static: False skips TRACING the heavy
                                  # domain branch entirely (sweep path —
                                  # lax.cond compiles both sides, and the
                                  # vmapped consolidation kernel must not
                                  # pay TPU compile time for a branch its
                                  # caller guarantees unreachable)
    sparse_k: int = 0,            # static: >0 packs take_exist as top-K
                                  # (count, index) pairs per group instead
                                  # of the dense [G, E] row.  The device
                                  # link here is a network tunnel, so the
                                  # result download is the sweep's floor:
                                  # dense take_exist is G*E (8*2048 f32 =
                                  # 64 KiB/sim) while a group of c pods
                                  # touches at most c existing nodes.
                                  # Caller guarantees K >= max group count
                                  # so the sparse form is lossless.
    sparse_n: int = 0,            # static: >0 packs take_new the same way
                                  # — top-K (count, index) pairs per group
                                  # plus the per-group nonzero COUNT, so
                                  # the host can verify losslessness (the
                                  # new-node fan-out, unlike take_exist's,
                                  # is only warm-start-estimated; on
                                  # overflow the caller re-runs dense).
                                  # The single-problem path's dense [G, N]
                                  # row is its dominant result download
                                  # the same way take_exist is the
                                  # sweep's.
    mask_packed: bool = False,    # static: group_mask arrives bit-packed
                                  # as [G, ceil(O/8)] uint8 (little bit
                                  # order) and is expanded on device —
                                  # the [G, O] bool row is the dominant
                                  # UPLOAD the same way take_exist is the
                                  # dominant download (O runs to ~11k
                                  # columns at full catalog), and the
                                  # tunnel makes bytes the cost.
    axis_name=None,               # static: set (to the mesh axis name)
                                  # ONLY inside a shard_map body — the
                                  # column axes (O and PT) then arrive as
                                  # per-device shards, the group-scan
                                  # state stays replicated, and every
                                  # column-axis winner selection reduces
                                  # locally then all-reduce-maxes over
                                  # the mesh (see _axmax).  None = the
                                  # single-device program, lowered
                                  # exactly as before this parameter
                                  # existed.
    explain: int = 0,             # static: placement-provenance aux
                                  # (ISSUE 13).  1 ("counts") appends
                                  # per-group elimination counts per
                                  # constraint class (KERNEL_CONSTRAINTS
                                  # order, [G, EXPLAIN_C]) + a reason
                                  # bitset [G], computed AFTER the scan
                                  # from the final state — purely
                                  # additive, the main outputs are
                                  # bit-identical to explain=0.  2
                                  # ("full") additionally appends the
                                  # [G, O] per-column eliminating-class
                                  # map (single-device only — the map is
                                  # column-sharded under a mesh and has
                                  # no replicated form).  Under a mesh,
                                  # counts combine via one psum over the
                                  # column shards.
    with_gang: int = 0,           # static: 0 skips TRACING the gang
                                  # branch entirely — gang-free problems
                                  # (every existing workload) lower to
                                  # the exact pre-gang program, so bit
                                  # parity with the pre-gang kernel is
                                  # by construction, and the sweep /
                                  # delta lanes never pay the branch's
                                  # compile time.  1 arms the atomic
                                  # K-node gang fill for groups with
                                  # group_gang set.
    with_priority: int = 0,       # static: 0 skips the priority
                                  # inversion-witness aux entirely —
                                  # priority-free problems lower to the
                                  # exact pre-priority program (bit
                                  # parity by construction, the
                                  # with_gang discipline).  1 appends
                                  # one additive [G] aux row AFTER the
                                  # explain aux: the per-group
                                  # inversion bit computed post-scan
                                  # from the strand outputs (no carry
                                  # change, no branch in the scan).
):
    G, RDIM = group_req.shape
    E = exist_remaining.shape[0]
    O = col_alloc.shape[0]
    PT = pt_alloc.shape[0]
    assert O == PT * zc, (O, PT, zc)
    _note_trace(G=G, E=E, O=O, N=max_nodes, D=group_dbase.shape[1],
                with_topology=with_topology, sparse_k=sparse_k,
                sparse_n=sparse_n, mask_packed=mask_packed,
                axis_name=axis_name, seeded=seed_used is not None,
                explain=explain, with_gang=with_gang,
                with_priority=with_priority)
    if explain >= 2:
        # the [G, O] class map is column-sharded under a mesh and the
        # shard_map out-spec is replicated — counts-only there
        assert axis_name is None, "explain=full has no sharded form"
    if mask_packed:
        # a bit-packed mask cannot arrive as a mesh shard: the byte axis
        # packs 8 columns and a shard boundary may split a byte
        assert axis_name is None, "mask_packed has no sharded form"
        group_mask = _expand_packed_mask(group_mask, O)

    def pt_expand(a_pt):
        # [N,PT] → [N,O]: the grid layout makes the (pool,type) axis a
        # pure reshape of the column axis — no gather, no scatter
        return jnp.broadcast_to(
            a_pt[:, :, None], (a_pt.shape[0], PT, zc)).reshape(
                a_pt.shape[0], O)

    def pt_any(a_col):
        # [N,O] bool → [N,PT] bool: any column of the block
        return a_col.reshape(a_col.shape[0], PT, zc).max(axis=-1)

    def slot_expand(a_slot):
        # [N,ZC] → [N,O]: tile a per-grid-slot mask across every
        # (pool,type) block — the grid makes domain membership a pure
        # function of the slot, so node→domain column masks need no
        # [D,O] gather
        return jnp.broadcast_to(
            a_slot[:, None, :], (a_slot.shape[0], PT, zc)).reshape(
                a_slot.shape[0], O)
    P = pool_limit.shape[0]
    D = group_dbase.shape[1]
    N = max_nodes
    dom_ids = jnp.arange(D, dtype=jnp.int32)
    idx = jnp.arange(N, dtype=jnp.int32)

    if seed_used is not None:
        # delta-seeded start: exist_remaining arrives already consumed by
        # the prefix (host replay, solver/delta.py), the seeded node
        # slots carry their used/colmask/pool state, and everything past
        # them is the ordinary unopened-slot zero state.  num_active is
        # derived from the seed mask, so the scan appends new nodes
        # exactly where the full solve's suffix would.
        # seed_colmask is padded to a non-empty bucket tier by the
        # caller (delta.SEED_BUCKETS), so the static row slice is
        # always well-formed
        colmask0 = jnp.zeros((N, O), bool).at[
            :seed_colmask.shape[0], :].set(seed_colmask)
        init = dict(
            exist_rem=exist_remaining,
            used=seed_used,
            colmask=colmask0,
            active=seed_active,
            node_pool=seed_pool,
            node_zone=jnp.full((N,), -1, jnp.int32),
            node_ct=jnp.full((N,), -1, jnp.int32),
            num_active=seed_active.astype(jnp.int32).sum(),
            limits=pool_limit,
        )
    else:
        init = dict(
            exist_rem=exist_remaining,
            used=jnp.zeros((N, RDIM), jnp.float32),
            colmask=jnp.zeros((N, O), bool),
            active=jnp.zeros((N,), bool),
            node_pool=jnp.zeros((N,), jnp.int32),
            node_zone=jnp.full((N,), -1, jnp.int32),
            node_ct=jnp.full((N,), -1, jnp.int32),
            num_active=jnp.int32(0),
            limits=pool_limit,
        )

    def _clamp_pool_limits(cap_n, node_pool, limits, req):
        # pool limits are COLLECTIVE: clamp each node's cap by what the
        # pool's budget leaves after earlier (lower-index) nodes of the same
        # pool take theirs — per-node clamping alone would let several nodes
        # of one pool jointly overrun the limit (P is static → unrolled)
        limit_cap = _fit_count(limits, req)                    # [P]
        for p in range(P):
            mask_p = node_pool == p
            cap_p = jnp.where(mask_p, cap_n, 0)
            before_p = jnp.cumsum(cap_p) - cap_p
            allowed = jnp.clip(limit_cap[p] - before_p, 0, None)
            cap_n = jnp.where(mask_p, jnp.minimum(cap_p, allowed), cap_n)
        return cap_n

    def step(carry, xs):
        (req, cnt, gmask, ecap, ncap, dsel,
         dbase, dcap, skew, mindom, delig, whole, gang) = xs

        def light(carry):
            exist_rem = carry["exist_rem"]
            used = carry["used"]
            colmask = carry["colmask"]
            active = carry["active"]
            node_pool = carry["node_pool"]
            num_active = carry["num_active"]
            limits = carry["limits"]

            # -- 1. existing nodes --------------------------------------
            cap_e = (jnp.minimum(_fit_count(exist_rem, req), ecap)
                     if E else jnp.zeros((0,), jnp.int32))
            take_e = (jnp.where(whole, _atomic_fill(cap_e, cnt),
                                _prefix_fill(cap_e, cnt))
                      if E else cap_e)
            exist_rem = exist_rem - take_e[:, None] * req if E else exist_rem
            c1 = cnt - (take_e.sum() if E else 0)

            # -- 2. in-flight nodes -------------------------------------
            # Capacity varies only per (pool,type): the fit math runs at
            # [N,PT] (≈6x narrower than [N,O] — zones×capacity-types
            # repeat the same allocatable row), and the per-column mask
            # reduces to PT eligibility by a segment-max. The [N,O,R]
            # chains this replaces were the kernel's dominant HBM traffic.
            avail_pt = pt_alloc[None, :, :] - used[:, None, :]     # [N,PT,R]
            cap_npt = _fit_count(avail_pt, req)                    # [N,PT]
            elig_pt = pt_any(colmask & gmask[None, :])             # [N,PT]
            cap_n = jnp.where(
                active,
                jnp.minimum(
                    _axmax(jnp.where(elig_pt, cap_npt, 0), axis_name,
                           axis=1), ncap),
                0)
            # pool-limit clamp: the prefix-residual form charges earlier
            # same-pool nodes that an ALL-or-nothing fill will never
            # touch, spuriously disqualifying the one node that could
            # hold the whole group — whole groups clamp each node
            # against the FULL pool budget instead (sound: exactly one
            # node takes, and its take stays within that budget)
            cap_n_pfx = _clamp_pool_limits(cap_n, node_pool, limits, req)
            cap_n_full = jnp.minimum(cap_n, _fit_count(limits, req)[node_pool])
            cap_n = jnp.where(whole, cap_n_full, cap_n_pfx)
            take_n = jnp.where(whole, _atomic_fill(cap_n, c1),
                               _prefix_fill(cap_n, c1))
            used = used + take_n[:, None] * req
            touched = take_n > 0
            colmask = jnp.where(touched[:, None], colmask & gmask[None, :], colmask)
            ok_pt = jnp.all(
                pt_alloc[None, :, :] - used[:, None, :] >= -EPS,
                axis=-1)                                           # [N,PT]
            colmask = colmask & pt_expand(ok_pt)
            pool_take = jax.ops.segment_sum(take_n.astype(jnp.float32), node_pool,
                                            num_segments=P)
            limits = limits - pool_take[:, None] * req
            c2 = c1 - take_n.sum()

            # -- 3. open new nodes --------------------------------------
            # Unrolled over pools in priority order (P is static): a pool
            # whose limit or catalog can't absorb the remaining pods falls
            # through to the next pool, like the oracle's per-pod cascade.
            per_col = jnp.minimum(_fit_count(col_alloc - col_daemon, req), ncap)
            col_feas = gmask & (per_col >= 1)
            c_rem = c2
            k_new_total = jnp.zeros((N,), jnp.int32)
            active_, node_pool_, num_active_ = active, node_pool, num_active
            for p in range(P):
                cols_p = col_feas & (col_pool == p)
                k_full = _axmax(jnp.where(cols_p, per_col, 0), axis_name)
                pool_room = jnp.all(limits[p] - pool_daemon[p] - req >= -EPS)
                can = (_axany(cols_p, axis_name) & pool_room
                       & (c_rem > 0) & (k_full > 0))
                # whole-node groups must land the ENTIRE remainder on one
                # node of one pool — a pool that can only take part of it
                # (column capacity, or budget after the one-node daemon
                # charge) would split the group across the pool cascade
                can = can & jnp.where(
                    whole,
                    (k_full >= c_rem) & (_fit_count(
                        (limits[p] - pool_daemon[p])[None, :],
                        req)[0] >= c_rem),
                    True)
                kf = jnp.maximum(k_full, 1)
                # budget-exact node count: affordable PODS first, then the
                # per-node daemon charge for the implied node count (two
                # passes, m only shrinks — sound since t·req + m·daemon ≤
                # limit after the second clamp). A full-node charge here
                # would open ZERO nodes whenever the remaining budget is
                # smaller than one maximal node, stranding pods that only
                # need a sliver of it.
                t = jnp.minimum(c_rem, _fit_count(limits[p][None, :], req)[0])
                m_t = -(-t // kf)
                t = jnp.minimum(t, _fit_count(
                    (limits[p] - m_t.astype(jnp.float32) * pool_daemon[p]
                     )[None, :], req)[0])
                m_need = jnp.where(can, -(-t // kf), 0)
                m = jnp.minimum(m_need, N - num_active_)
                newmask = (idx >= num_active_) & (idx < num_active_ + m)
                pos = idx - num_active_
                taken_new = jnp.minimum(t, m * k_full)
                k_node = jnp.where(
                    newmask,
                    jnp.where(pos == m - 1, taken_new - (m - 1) * k_full, k_full),
                    0)
                new_used = pool_daemon[p][None, :] + k_node[:, None].astype(jnp.float32) * req
                used = jnp.where(newmask[:, None], new_used, used)
                new_ok_pt = jnp.all(
                    pt_alloc[None, :, :] - new_used[:, None, :] >= -EPS,
                    axis=-1)
                new_colmask = cols_p[None, :] & pt_expand(new_ok_pt)
                colmask = jnp.where(newmask[:, None], new_colmask, colmask)
                active_ = active_ | newmask
                node_pool_ = jnp.where(newmask, jnp.int32(p), node_pool_)
                num_active_ = num_active_ + m
                limits = limits.at[p].add(
                    -(m.astype(jnp.float32) * pool_daemon[p]
                      + taken_new.astype(jnp.float32) * req))
                k_new_total = k_new_total + k_node
                c_rem = c_rem - taken_new

            out_carry = dict(exist_rem=exist_rem, used=used, colmask=colmask,
                             active=active_, node_pool=node_pool_,
                             node_zone=carry["node_zone"],
                             node_ct=carry["node_ct"],
                             num_active=num_active_, limits=limits)
            out = dict(take_exist=take_e, take_new=take_n + k_new_total,
                       unsched=c_rem,
                       dom_placed=jnp.zeros((D,), jnp.int32))
            return out_carry, out

        def heavy(carry):
            exist_rem = carry["exist_rem"]
            used = carry["used"]
            colmask = carry["colmask"]
            active = carry["active"]
            node_pool = carry["node_pool"]
            node_zone = carry["node_zone"]
            node_ct = carry["node_ct"]
            num_active = carry["num_active"]
            limits = carry["limits"]

            col_dom = jnp.where(dsel == 1, col_zone, col_ct)       # [O]
            ex_dom = (jnp.where(dsel == 1, exist_zone, exist_ct)
                      if E else jnp.zeros((0,), jnp.int32))
            dom_cols = col_dom[None, :] == dom_ids[:, None]        # [D, O]
            dom_ex = (ex_dom[None, :] == dom_ids[:, None]
                      if E else jnp.zeros((D, 0), bool))           # [D, E]

            # -- capacity estimates per domain (for the water-fill) -----
            cap_e = (jnp.minimum(_fit_count(exist_rem, req), ecap)
                     if E else jnp.zeros((0,), jnp.int32))
            cap_ed = (jnp.where(dom_ex, cap_e[None, :], 0)
                      if E else jnp.zeros((D, 0), jnp.int32))      # [D, E]

            # same pt-granular fit as the light branch ([N,PT] then a
            # reshape-expand) — the grid layout inflates O with invalid
            # combos, so the [N,O,R] chain would now cost MORE than before
            cap_npt_h = _fit_count(
                pt_alloc[None, :, :] - used[:, None, :], req)     # [N,PT]
            cap_no = jnp.where(colmask & gmask[None, :],
                               pt_expand(cap_npt_h), 0)           # [N,O]
            # per-domain max via the grid: max over (pool,type) blocks
            # per slot, then combine the ZC slots by their domain id — a
            # reshape + tiny [N,ZC,D] combine instead of a scatter-based
            # segment_max over the O axis
            zc_dom = col_dom[:zc]                              # [ZC]
            if axis_name is not None:
                # the per-slot domain pattern must be the GLOBAL leading
                # block's, not each shard's: a shard of pure padding (or
                # a dense zc=1 layout, where every column carries its own
                # domain) would otherwise hand every device a different
                # zc_dom.  Shard 0 owns the global first block.
                zc_dom = jax.lax.all_gather(zc_dom, axis_name)[0]
            slotmax = _axmax(cap_no.reshape(-1, PT, zc), axis_name,
                             axis=1)                           # [N, ZC]
            cap_nd = jnp.where(
                zc_dom[None, :, None] == dom_ids[None, None, :],
                slotmax[:, :, None], 0).max(axis=1).T          # [D, N]
            cap_nd = jnp.minimum(cap_nd, ncap)
            cap_nd = jnp.where(active[None, :], cap_nd, 0)
            # each in-flight node serves exactly ONE domain (placing a
            # zone-spread pod pins the node, as the oracle's requirement
            # narrowing does); break capacity ties by rotating over nodes
            # so equal nodes spread across domains. Capacity saturates at
            # the group count: beyond cnt it buys nothing, and without the
            # clamp a domain whose best column is marginally larger would
            # win EVERY unpinned node and starve the other domains. The
            # rotation cycles over the REAL domain count (not the padded
            # bucket D): modulo the pad width, the residues are skewed and
            # most unpinned nodes land on one domain.
            d_real = jnp.maximum(_axmax(col_dom, axis_name) + 1, 1)
            score = (jnp.minimum(cap_nd, cnt) * jnp.int32(D + 1)
                     + (idx[None, :] + dom_ids[:, None]) % d_real)
            bd = jnp.argmax(score, axis=0).astype(jnp.int32)        # [N]
            sel_nd = dom_ids[:, None] == bd[None, :]
            cap_nd = jnp.where(sel_nd, cap_nd, 0)

            per_col = jnp.minimum(_fit_count(col_alloc - col_daemon, req), ncap)
            col_feas = gmask & (per_col >= 1)
            kfull_pd = []
            for p in range(P):
                cols_p = col_feas & (col_pool == p)
                kfull_pd.append(jnp.where(dom_cols & cols_p[None, :],
                                          per_col[None, :], 0).max(-1))  # [D]
            kfull_pd = jnp.stack(kfull_pd)                          # [P, D]
            if axis_name is not None:
                # one all-reduce for the whole [P, D] winner table
                # instead of P×D scalar collectives
                kfull_pd = jax.lax.pmax(kfull_pd, axis_name)
            rooms = jnp.stack([
                jnp.all(limits[p] - pool_daemon[p] - req >= -EPS)
                for p in range(P)])                                 # [P]
            # new-node pods per domain, clamped by what the pool budget can
            # actually buy — an unclamped estimate makes the water-fill
            # promise quotas the open-new step then can't honor
            afford = jnp.stack([
                _fit_count(limits[p][None, :], req)[0]
                for p in range(P)])                                 # [P]
            new_est = jnp.where(
                rooms[:, None],
                jnp.minimum((N - num_active) * kfull_pd, afford[:, None]),
                0).max(0)                                           # [D]
            capacity = cap_ed.sum(-1) + cap_nd.sum(-1) + new_est    # [D]
            # the pool budget is SHARED across domains (existing-node fills
            # don't consume it; in-flight and new nodes do): cap the group
            # count by the total affordable so the water-fill plans quotas
            # the budget can honor — an overshooting plan starves whichever
            # domain fills last, and the repair pass then strips its
            # placements back to the skew ceiling, stranding pods the
            # oracle would have placed in a balanced [51,50,50] shape.
            # NOT gated by `rooms`: in-flight fills charge only req (no
            # per-node daemon), so a pool without room for one more whole
            # node can still fund fills on already-open nodes.
            # Accumulate in f32: each pool's afford saturates at 2^30, so
            # an int32 sum over 2+ unlimited pools wraps negative and the
            # whole want-plan goes garbage (pods silently dropped)
            afford_total = afford.astype(jnp.float32).sum()
            cnt_eff = jnp.minimum(
                cnt.astype(jnp.float32),
                (cap_ed.sum().astype(jnp.float32) if E else 0.0)
                + afford_total).astype(jnp.int32)
            want = _water_fill(cnt_eff, dbase, jnp.minimum(capacity, dcap),
                               delig, skew, mindom)                  # [D]
            unplaceable = cnt - want.sum()

            # -- 1. existing nodes, per domain --------------------------
            if E:
                take_ed = jax.vmap(_prefix_fill)(cap_ed, want)       # [D, E]
                take_e = take_ed.sum(0)
                exist_rem = exist_rem - take_e[:, None] * req
                want = want - take_ed.sum(-1)
            else:
                take_e = jnp.zeros((0,), jnp.int32)

            # -- 2. in-flight nodes, per domain -------------------------
            # clamp by the domain's want BEFORE the budget cumsum: the
            # collective-limit clamp reserves headroom for earlier-indexed
            # nodes' caps, and an unclamped full-node cap (~the whole
            # node) would eat the entire pool budget on the first few
            # nodes, zeroing the later-indexed nodes the per-domain
            # prefix fill actually needs
            cap_nd = jnp.minimum(cap_nd, want[:, None])
            cap_n_flat = _clamp_pool_limits(cap_nd.sum(0), node_pool, limits, req)
            cap_nd = jnp.minimum(cap_nd, cap_n_flat[None, :])
            take_nd = jax.vmap(_prefix_fill)(cap_nd, want)           # [D, N]
            take_n = take_nd.sum(0)
            used = used + take_n[:, None] * req
            touched = take_n > 0
            node_dcols = slot_expand(zc_dom[None, :] == bd[:, None])  # [N, O]
            colmask = jnp.where(touched[:, None],
                                colmask & gmask[None, :] & node_dcols, colmask)
            ok_pt = jnp.all(
                pt_alloc[None, :, :] - used[:, None, :] >= -EPS, axis=-1)
            colmask = colmask & pt_expand(ok_pt)
            node_zone = jnp.where(touched & (dsel == 1), bd, node_zone)
            node_ct = jnp.where(touched & (dsel == 2), bd, node_ct)
            pool_take = jax.ops.segment_sum(take_n.astype(jnp.float32), node_pool,
                                            num_segments=P)
            limits = limits - pool_take[:, None] * req
            want = want - take_nd.sum(-1)

            # -- 3. open new nodes, per pool × domain -------------------
            k_new_total = jnp.zeros((N,), jnp.int32)
            new_dom_placed = jnp.zeros((D,), jnp.int32)
            active_, node_pool_, num_active_ = active, node_pool, num_active
            for p in range(P):
                cols_p = col_feas & (col_pool == p)
                kfull_d = kfull_pd[p]                                # [D]
                # budget allocation over domains shares the pool limit
                # sequentially (D is static → unrolled, cheap [R] math)
                rem_budget = limits[p]
                slots_left = N - num_active_
                m_list, taken_list = [], []
                for d in range(D):
                    can = (kfull_d[d] > 0) & (want[d] > 0)
                    kf = jnp.maximum(kfull_d[d], 1)
                    # budget-exact, as in the light branch: affordable pods
                    # first, then daemon for the implied node count — never
                    # the full-node overcharge
                    t = jnp.minimum(want[d],
                                    _fit_count(rem_budget[None, :], req)[0])
                    m_t = -(-t // kf)
                    t = jnp.minimum(t, _fit_count(
                        (rem_budget - m_t.astype(jnp.float32) * pool_daemon[p]
                         )[None, :], req)[0])
                    m_need = jnp.where(can, -(-t // kf), 0)
                    m_d = jnp.minimum(m_need, slots_left)
                    taken_d = jnp.minimum(t, m_d * kfull_d[d])
                    rem_budget = rem_budget - (
                        m_d.astype(jnp.float32) * pool_daemon[p]
                        + taken_d.astype(jnp.float32) * req)
                    slots_left = slots_left - m_d
                    m_list.append(m_d)
                    taken_list.append(taken_d)
                m_d = jnp.stack(m_list)                              # [D]
                taken_d = jnp.stack(taken_list)                      # [D]
                starts = num_active_ + jnp.cumsum(m_d) - m_d         # [D]
                in_dom = ((idx[None, :] >= starts[:, None])
                          & (idx[None, :] < (starts + m_d)[:, None]))  # [D, N]
                is_last = idx[None, :] == (starts + m_d - 1)[:, None]
                k_dn = jnp.where(
                    in_dom,
                    jnp.where(is_last,
                              (taken_d - (m_d - 1) * kfull_d)[:, None],
                              kfull_d[:, None]),
                    0)                                               # [D, N]
                k_node = k_dn.sum(0)                                 # [N]
                newmask = in_dom.any(0)
                new_used = (pool_daemon[p][None, :]
                            + k_node[:, None].astype(jnp.float32) * req)
                used = jnp.where(newmask[:, None], new_used, used)
                new_bd = (in_dom * dom_ids[:, None]).sum(0).astype(jnp.int32)
                nd_cols = slot_expand(zc_dom[None, :] == new_bd[:, None])
                new_ok_pt = jnp.all(
                    pt_alloc[None, :, :] - new_used[:, None, :] >= -EPS,
                    axis=-1)
                new_colmask = nd_cols & cols_p[None, :] & pt_expand(new_ok_pt)
                colmask = jnp.where(newmask[:, None], new_colmask, colmask)
                node_zone = jnp.where(newmask & (dsel == 1), new_bd, node_zone)
                node_ct = jnp.where(newmask & (dsel == 2), new_bd, node_ct)
                active_ = active_ | newmask
                node_pool_ = jnp.where(newmask, jnp.int32(p), node_pool_)
                num_active_ = num_active_ + m_d.sum()
                limits = limits.at[p].add(
                    -(m_d.sum().astype(jnp.float32) * pool_daemon[p]
                      + taken_d.sum().astype(jnp.float32) * req))
                k_new_total = k_new_total + k_node
                new_dom_placed = new_dom_placed + taken_d
                want = want - taken_d

            dom_placed = ((take_ed.sum(-1) if E else 0)
                          + take_nd.sum(-1) + new_dom_placed)
            out_carry = dict(exist_rem=exist_rem, used=used, colmask=colmask,
                             active=active_, node_pool=node_pool_,
                             node_zone=node_zone, node_ct=node_ct,
                             num_active=num_active_, limits=limits)
            out = dict(take_exist=take_e, take_new=take_n + k_new_total,
                       unsched=unplaceable + want.sum(),
                       dom_placed=dom_placed)
            return out_carry, out

        def gang_fill(carry):
            # -- atomic K-node gang fill (ISSUE 15) ---------------------
            # The whole-node all-or-nothing fill generalized to MANY
            # nodes in ONE adjacency domain.  For every domain this
            # computes the EXACT candidate fill — the light cascade
            # (existing → in-flight → open-new) restricted to that
            # domain's columns/nodes against an independent copy of the
            # pool budget (sound: at most one domain commits) — then
            # commits the feasible domain of minimal trial RANK (dbase
            # carries the encoder's lexicographic domain order, the
            # same order the oracle's trial loop walks) and discards
            # every other candidate.  "Bit-exact rollback" is
            # structural: a non-winning (or infeasible-everywhere)
            # candidate fill is never applied to the carry at all.
            # dsel names the adjacency axis (1 zone/slice, 2
            # capacity-type/rack); a domain-free gang (dsel=0) maps
            # every column/node to domain 0 and the machinery
            # degenerates to a single global trial.
            # REPLAY CONTRACT (ISSUE 20): solver/delta.py build()/
            # merge() host-replay a prefix gang row from the recorded
            # winner pins instead of re-running this fill — the
            # winner-domain column narrowing (dcols below), the
            # touched-node colmask update, and the node_zone/node_ct
            # pin writes are mirrored there op-for-op.  Changing the
            # winner selection, the narrowing masks, or the pin
            # arithmetic here requires the same change in delta.py or
            # the seeded merge loses bit parity on gang prefixes.
            exist_rem = carry["exist_rem"]
            used = carry["used"]
            colmask = carry["colmask"]
            active = carry["active"]
            node_pool = carry["node_pool"]
            node_zone = carry["node_zone"]
            node_ct = carry["node_ct"]
            num_active = carry["num_active"]
            limits = carry["limits"]

            col_dom = jnp.where(
                dsel == 1, col_zone,
                jnp.where(dsel == 2, col_ct, jnp.zeros_like(col_zone)))
            dom_cols = col_dom[None, :] == dom_ids[:, None]    # [D, O]
            if E:
                ex_dom = jnp.where(
                    dsel == 1, exist_zone,
                    jnp.where(dsel == 2, exist_ct,
                              jnp.zeros_like(exist_zone)))
                dom_ex = ex_dom[None, :] == dom_ids[:, None]   # [D, E]

            # -- 1. existing-node candidate fills per domain ------------
            want0 = jnp.full((D,), cnt, jnp.int32)
            if E:
                cap_e = jnp.minimum(_fit_count(exist_rem, req), ecap)
                cap_ed = jnp.where(dom_ex, cap_e[None, :], 0)  # [D, E]
                take_ed = jax.vmap(_prefix_fill)(cap_ed, want0)
                rem1 = cnt - take_ed.sum(-1)                   # [D]
            else:
                take_ed = jnp.zeros((D, 0), jnp.int32)
                rem1 = want0

            # -- 2. in-flight candidate fills per domain ----------------
            # pt-granular fit + the zc-slot domain combine, exactly the
            # heavy branch's discipline; a node already pinned to some
            # domain is excluded from the others automatically (its
            # colmask was narrowed to its domain's columns)
            cap_npt = _fit_count(
                pt_alloc[None, :, :] - used[:, None, :], req)  # [N, PT]
            cap_no = jnp.where(colmask & gmask[None, :],
                               pt_expand(cap_npt), 0)          # [N, O]
            zc_dom_g = col_dom[:zc]                            # [ZC]
            if axis_name is not None:
                # shard 0 owns the global leading block (the heavy
                # branch's zc_dom rule — a pure-padding shard must see
                # the global slot→domain map)
                zc_dom_g = jax.lax.all_gather(zc_dom_g, axis_name)[0]
            slotmax = _axmax(cap_no.reshape(-1, PT, zc), axis_name,
                             axis=1)                           # [N, ZC]
            cap_nd = jnp.where(
                zc_dom_g[None, :, None] == dom_ids[None, None, :],
                slotmax[:, :, None], 0).max(axis=1).T          # [D, N]
            cap_nd = jnp.minimum(cap_nd, ncap)
            cap_nd = jnp.where(active[None, :], cap_nd, 0)
            cap_nd = jax.vmap(
                lambda c: _clamp_pool_limits(c, node_pool, limits,
                                             req))(cap_nd)
            take_nd = jax.vmap(_prefix_fill)(cap_nd, rem1)     # [D, N]
            rem2 = rem1 - take_nd.sum(-1)                      # [D]

            # -- 3. open-new candidate cascade per domain ---------------
            per_col = jnp.minimum(
                _fit_count(col_alloc - col_daemon, req), ncap)
            col_feas = gmask & (per_col >= 1)
            kfull_pd = jnp.stack([
                jnp.where(dom_cols & (col_feas
                                      & (col_pool == p))[None, :],
                          per_col[None, :], 0).max(-1)
                for p in range(P)])                            # [P, D]
            if axis_name is not None:
                # one all-reduce for the whole winner table (heavy rule)
                kfull_pd = jax.lax.pmax(kfull_pd, axis_name)
            # independent per-domain budget copies, pre-charged with the
            # domain's own in-flight take (the commit charges in that
            # order too)
            limits_d = jnp.broadcast_to(limits[None], (D, P, RDIM))
            pool_take_d = jax.vmap(lambda t: jax.ops.segment_sum(
                t.astype(jnp.float32), node_pool,
                num_segments=P))(take_nd)                      # [D, P]
            limits_d = (limits_d
                        - pool_take_d[:, :, None] * req[None, None, :])
            c_rem_d = rem2
            k_new_d = jnp.zeros((D, N), jnp.int32)
            new_pool_d = jnp.zeros((D, N), jnp.int32)
            newmask_d = jnp.zeros((D, N), bool)
            na_d = jnp.zeros((D,), jnp.int32) + num_active
            for p in range(P):
                kf_raw = kfull_pd[p]                           # [D]
                lim_p = limits_d[:, p]                         # [D, R]
                pool_room = jnp.all(
                    lim_p - pool_daemon[p][None, :] - req[None, :]
                    >= -EPS, axis=-1)                          # [D]
                can = pool_room & (c_rem_d > 0) & (kf_raw > 0)
                kf = jnp.maximum(kf_raw, 1)
                # budget-exact node count, the light branch's two-pass
                # discipline: affordable pods first, then the per-node
                # daemon charge for the implied node count
                t = jnp.minimum(c_rem_d, _fit_count(lim_p, req))
                m_t = -(-t // kf)
                t = jnp.minimum(t, _fit_count(
                    lim_p - m_t[:, None].astype(jnp.float32)
                    * pool_daemon[p][None, :], req))
                m_need = jnp.where(can, -(-t // kf), 0)
                m = jnp.minimum(m_need, N - na_d)
                newmask = ((idx[None, :] >= na_d[:, None])
                           & (idx[None, :] < (na_d + m)[:, None]))
                pos = idx[None, :] - na_d[:, None]
                taken = jnp.minimum(t, m * kf_raw)
                k_node = jnp.where(
                    newmask,
                    jnp.where(pos == (m - 1)[:, None],
                              (taken - (m - 1) * kf_raw)[:, None],
                              kf_raw[:, None]),
                    0)
                k_new_d = k_new_d + k_node
                new_pool_d = jnp.where(newmask, jnp.int32(p),
                                       new_pool_d)
                newmask_d = newmask_d | newmask
                na_d = na_d + m
                limits_d = limits_d.at[:, p].add(
                    -(m[:, None].astype(jnp.float32)
                      * pool_daemon[p][None, :]
                      + taken[:, None].astype(jnp.float32)
                      * req[None, :]))
                c_rem_d = c_rem_d - taken
            placed_d = ((take_ed.sum(-1) if E else 0)
                        + take_nd.sum(-1) + (rem2 - c_rem_d))  # [D]

            # -- winner: feasible domain of minimal trial rank ----------
            feas = delig & (placed_d >= cnt)
            rank = jnp.where(feas, dbase, jnp.int32(_BIG))
            w = jnp.argmin(rank).astype(jnp.int32)
            ok = (rank[w] < _BIG) & (cnt > 0)

            # -- commit the winner (everything else is never applied) ---
            if E:
                take_e = jnp.where(ok, take_ed[w],
                                   jnp.zeros_like(cap_e))
                exist_rem = exist_rem - take_e[:, None] * req
            else:
                take_e = jnp.zeros((0,), jnp.int32)
            take_n = jnp.where(ok, take_nd[w], 0)              # [N]
            used = used + take_n[:, None] * req
            touched = take_n > 0
            dcols = slot_expand((zc_dom_g == w)[None, :])      # [1, O]
            colmask = jnp.where(touched[:, None],
                                colmask & gmask[None, :] & dcols,
                                colmask)
            ok_pt = jnp.all(
                pt_alloc[None, :, :] - used[:, None, :] >= -EPS,
                axis=-1)
            colmask = colmask & pt_expand(ok_pt)
            pool_take = jax.ops.segment_sum(
                take_n.astype(jnp.float32), node_pool, num_segments=P)
            limits = limits - pool_take[:, None] * req

            k_new = jnp.where(ok, k_new_d[w], 0)               # [N]
            newmask = jnp.where(ok, newmask_d[w], False)
            new_pool = new_pool_d[w]                           # [N]
            new_used = (pool_daemon[new_pool]
                        + k_new[:, None].astype(jnp.float32) * req)
            used = jnp.where(newmask[:, None], new_used, used)
            new_cols = (col_feas[None, :]
                        & (col_pool[None, :] == new_pool[:, None])
                        & dcols)
            new_ok_pt = jnp.all(
                pt_alloc[None, :, :] - new_used[:, None, :] >= -EPS,
                axis=-1)
            new_colmask = new_cols & pt_expand(new_ok_pt)
            colmask = jnp.where(newmask[:, None], new_colmask, colmask)
            active_ = active | newmask
            node_pool_ = jnp.where(newmask, new_pool, node_pool)
            num_active_ = num_active + newmask.astype(jnp.int32).sum()
            for p in range(P):
                on_p = newmask & (new_pool == p)
                m_p = on_p.astype(jnp.float32).sum()
                taken_p = jnp.where(on_p, k_new,
                                    0).astype(jnp.float32).sum()
                limits = limits.at[p].add(
                    -(m_p * pool_daemon[p] + taken_p * req))
            # pin every node the gang touched to the winning domain so
            # decode narrows the claims (rank adjacency must survive
            # launch) — exactly the heavy branch's pinning discipline
            node_zone = jnp.where((touched | newmask) & (dsel == 1),
                                  w, node_zone)
            node_ct = jnp.where((touched | newmask) & (dsel == 2),
                                w, node_ct)

            out_carry = dict(exist_rem=exist_rem, used=used,
                             colmask=colmask, active=active_,
                             node_pool=node_pool_, node_zone=node_zone,
                             node_ct=node_ct, num_active=num_active_,
                             limits=limits)
            # dom_placed carries the per-domain CANDIDATE totals (what
            # each domain could have held, saturated at the gang size)
            # — the explain tree's nearest-domain/deficit answer
            out = dict(take_exist=take_e, take_new=take_n + k_new,
                       unsched=jnp.where(ok, 0, cnt),
                       dom_placed=jnp.minimum(placed_d,
                                              cnt).astype(jnp.int32))
            return out_carry, out

        if with_gang:
            def nongang(c):
                if not with_topology:
                    return light(c)
                return jax.lax.cond(dsel > 0, heavy, light, c)
            return jax.lax.cond(gang, gang_fill, nongang, carry)
        if not with_topology:
            return light(carry)
        return jax.lax.cond(dsel > 0, heavy, light, carry)

    xs = (group_req, group_count, group_mask, exist_cap, group_ncap,
          group_dsel, group_dbase, group_dcap, group_skew, group_mindom,
          group_delig, group_whole, group_gang)
    final, outs = jax.lax.scan(step, init, xs)
    # Results are packed into ONE flat f32 buffer: each host pull pays a
    # full round trip on the device link, so small arrays cost one RTT each
    # — one concatenated buffer costs one. colmask [N,O] stays on device
    # entirely; the host reconstructs it from (take_new, used, group_mask,
    # node_zone/node_ct).
    if sparse_k:
        # compact the nonzero entries of each [E] row into K slots by
        # prefix-sum rank + scatter (mode=drop swallows the impossible
        # overflow) — NOT lax.top_k, whose sort costs more than the rest
        # of the result pack combined at E=2048
        te = outs["take_exist"]                              # [G, E] i32
        nz = te > 0
        rank = jnp.cumsum(nz.astype(jnp.int32), axis=1) - 1  # [G, E]
        slot = jnp.where(nz, rank, sparse_k)                 # K = dropped
        gi = jnp.broadcast_to(
            jnp.arange(te.shape[0], dtype=jnp.int32)[:, None], te.shape)
        ei = jnp.broadcast_to(
            jnp.arange(te.shape[1], dtype=jnp.int32)[None, :], te.shape)
        te_cnt = jnp.zeros((te.shape[0], sparse_k), te.dtype).at[
            gi, slot].set(te, mode="drop")
        te_idx = jnp.zeros((te.shape[0], sparse_k), jnp.int32).at[
            gi, slot].set(ei, mode="drop")
        head = [te_cnt.astype(jnp.float32).reshape(-1),      # G*K
                te_idx.astype(jnp.float32).reshape(-1)]      # G*K
    else:
        head = [outs["take_exist"].astype(jnp.float32).reshape(-1)]  # G*E
    if sparse_n:
        # same prefix-sum-rank compaction for the NEW-node rows, plus the
        # per-group nonzero count: unlike take_exist (where K bounds the
        # group size by construction), the new-node fan-out is only
        # estimated from the previous solve, so the count row is the
        # host's lossless check — overflow re-runs dense (solve.py)
        tn = outs["take_new"]                                # [G, N] i32
        nzn = tn > 0
        rankn = jnp.cumsum(nzn.astype(jnp.int32), axis=1) - 1
        slotn = jnp.where(nzn, rankn, sparse_n)              # Kn = dropped
        gin = jnp.broadcast_to(
            jnp.arange(tn.shape[0], dtype=jnp.int32)[:, None], tn.shape)
        nin = jnp.broadcast_to(
            jnp.arange(tn.shape[1], dtype=jnp.int32)[None, :], tn.shape)
        tn_cnt = jnp.zeros((tn.shape[0], sparse_n), tn.dtype).at[
            gin, slotn].set(tn, mode="drop")
        tn_idx = jnp.zeros((tn.shape[0], sparse_n), jnp.int32).at[
            gin, slotn].set(nin, mode="drop")
        mid = [tn_cnt.astype(jnp.float32).reshape(-1),       # G*Kn
               tn_idx.astype(jnp.float32).reshape(-1),       # G*Kn
               nzn.sum(-1).astype(jnp.float32)]              # G (nnz row)
    else:
        mid = [outs["take_new"].astype(jnp.float32).reshape(-1)]  # G*N
    aux = []
    if explain:
        # -- placement-provenance aux (ISSUE 13): per-group elimination
        # counts per constraint class, judged against the FINAL solve
        # state (the explain question is "why can't this group take more
        # columns NOW").  Purely additive — appended after the base
        # block so every existing unpack offset is untouched, and the
        # main outputs are bit-identical to explain=0.  All column-axis
        # math runs at PT granularity (capacity varies only per
        # (pool,type) block) and combines under a mesh via ONE psum.
        pt_daemon = col_daemon.reshape(PT, zc, RDIM)[:, 0]     # [PT, R]
        pt_pool = col_pool.reshape(PT, zc)[:, 0]               # [PT]
        gmask_pt = group_mask.reshape(G, PT, zc)
        cols_per_block = gmask_pt.sum(-1).astype(jnp.int32)    # [G, PT]
        # fit: one pod of the group cannot land on an EMPTY node of the
        # column (static infeasibility — the encode-time mask admits
        # the column for labels, but the resources never fit)
        fits_pt = jnp.all(
            pt_alloc[None, :, :] - pt_daemon[None, :, :]
            - group_req[:, None, :] >= -EPS, axis=-1)          # [G, PT]
        elim_fit = jnp.where(~fits_pt, cols_per_block, 0).sum(-1)
        # limit: the pool's FINAL remaining budget cannot fund one more
        # pod plus the per-node daemon charge
        lim_ok = jnp.all(
            final["limits"][None, :, :] - pool_daemon[None, :, :]
            - group_req[:, None, :] >= -EPS, axis=-1)          # [G, P]
        lim_ok_pt = lim_ok[:, pt_pool]                         # [G, PT]
        elim_limit = jnp.where(fits_pt & ~lim_ok_pt,
                               cols_per_block, 0).sum(-1)
        # topology: columns whose domain is ineligible or at the skew
        # ceiling (the same floor arithmetic as _water_fill's minDomains
        # handling); domain-of-slot via the zc grid, exactly the heavy
        # branch's zc_dom discipline.  dom_placed is each group's OWN
        # step output — final for that group's constraint by the
        # kernel's self-selecting invariant (module docstring: only
        # self-match spread reaches the kernel, so no later group's
        # placements count toward this group's selector)
        big_i = jnp.int32(2 ** 29)
        f_dom = group_dbase + outs["dom_placed"]               # [G, D]
        m_elig = jnp.where(group_delig, f_dom, big_i).min(-1)  # [G]
        pop = (jnp.where(group_delig, f_dom, 0) > 0).sum(-1)
        m_floor = jnp.where((group_mindom > 0) & (pop < group_mindom),
                            0, m_elig)
        ceiling = m_floor + group_skew                         # [G]
        blocked_dom = (~group_delig) | (f_dom >= ceiling[:, None])
        zc_zone, zc_ct = col_zone[:zc], col_ct[:zc]
        if axis_name is not None:
            # shard 0 owns the global leading block (same reason the
            # heavy branch all_gathers its zc_dom)
            zc_zone = jax.lax.all_gather(zc_zone, axis_name)[0]
            zc_ct = jax.lax.all_gather(zc_ct, axis_name)[0]
        slot_dom = jnp.where((group_dsel == 1)[:, None],
                             zc_zone[None, :], zc_ct[None, :])  # [G, ZC]
        slot_blocked = jnp.take_along_axis(
            blocked_dom, jnp.clip(slot_dom, 0, D - 1), axis=1)  # [G, ZC]
        # the classes PARTITION the eliminated columns with the same
        # precedence as the full-mode map (fit > limit > topology >
        # whole) — overlapping counts would sum past columns_total and
        # contradict the map's per-column verdicts in the same tree
        ok_pt = fits_pt & lim_ok_pt                             # [G, PT]
        elim_topo = jnp.where(
            (group_dsel > 0)[:, None, None] & slot_blocked[:, None, :]
            & ok_pt[:, :, None],
            gmask_pt.astype(jnp.int32), 0).sum((1, 2))
        # whole-node gating: a stranded all-or-nothing group failed
        # atomically on every admitted column no other class claims
        # (whole + dynamic spread is Unsupported at encode, so topology
        # never overlaps)
        stranded = outs["unsched"] > 0
        if with_gang:
            # gang strands attribute to the SAME whole_node class (the
            # gang fill is the whole-node fill's K-node generalization)
            # but a gang carries dsel>0, so the topology class CAN
            # overlap here — keep the partition by excluding columns
            # topology already claimed (the map's precedence)
            whole_like = group_whole | group_gang
            topo_sel = ((group_dsel > 0)[:, None, None]
                        & slot_blocked[:, None, :])
            whole_cols = jnp.where(
                ok_pt[:, :, None] & ~topo_sel,
                gmask_pt.astype(jnp.int32), 0).sum((1, 2))
            elim_whole = jnp.where(whole_like & stranded, whole_cols, 0)
        else:
            elim_whole = jnp.where(
                group_whole & stranded,
                jnp.where(ok_pt, cols_per_block, 0).sum(-1), 0)
        local = jnp.stack(
            [elim_fit, elim_limit, elim_topo, elim_whole],
            axis=1).astype(jnp.int32)                           # [G, 4]
        if axis_name is not None:
            local = jax.lax.psum(local, axis_name)
        # slots: node-axis exhaustion — replicated scalar state, so it
        # joins AFTER the psum (a psum would multiply it by the mesh)
        slots_exhausted = (stranded
                           & (final["num_active"] >= N)).astype(jnp.int32)
        counts = jnp.concatenate([local, slots_exhausted[:, None]],
                                 axis=1)                        # [G, C]
        weights = jnp.asarray([1 << i for i in range(EXPLAIN_C)],
                              jnp.int32)
        bits = ((counts > 0).astype(jnp.int32)
                * weights[None, :]).sum(-1)                     # [G]
        aux = [counts.astype(jnp.float32).reshape(-1),          # G*C
               bits.astype(jnp.float32)]                        # G
        if explain >= 2:
            # per-column eliminating class (1-based into
            # KERNEL_CONSTRAINTS; 0 = not eliminated on device):
            # precedence fit > limit > topology > whole — the first
            # constraint that strikes a column is the one named
            fits_col = jnp.repeat(fits_pt, zc, axis=1)          # [G, O]
            lim_col = jnp.repeat(lim_ok_pt, zc, axis=1)
            col_dom = jnp.where((group_dsel == 1)[:, None],
                                col_zone[None, :], col_ct[None, :])
            col_blocked = jnp.take_along_axis(
                blocked_dom, jnp.clip(col_dom, 0, D - 1), axis=1)
            cls_map = jnp.where(group_mask & ~fits_col, 1, 0)
            cls_map = jnp.where(group_mask & fits_col & ~lim_col,
                                2, cls_map)
            cls_map = jnp.where(
                group_mask & (group_dsel > 0)[:, None] & col_blocked
                & (cls_map == 0), 3, cls_map)
            whole_map = (((group_whole | group_gang) if with_gang
                          else group_whole) & stranded)
            cls_map = jnp.where(
                group_mask & whole_map[:, None]
                & (cls_map == 0), 4, cls_map)
            aux.append(cls_map.astype(jnp.float32).reshape(-1))  # G*O
    if with_priority:
        # -- priority inversion witness (ISSUE 16), judged post-scan from
        # the strand outputs alone: encode's host-side band re-sort means
        # a HIGHER band always scans first, so "an earlier group
        # stranded with strictly higher priority than a group that still
        # placed" is exactly a band exhausting while a lower band
        # succeeds — the trigger the decode reclassifies as
        # PriorityBandExhausted and the preemption planner acts on.
        # Exclusive running max of the stranded groups' priorities
        # (replicated group-axis state — no psum under a mesh).
        gp = (jnp.zeros(G, jnp.int32) if group_prio is None
              else group_prio.astype(jnp.int32))
        neg = jnp.int32(-(2 ** 31) + 1)
        stranded_p = outs["unsched"] > 0
        strand_seen = jax.lax.cummax(jnp.where(stranded_p, gp, neg))
        strand_before = jnp.concatenate(
            [jnp.full((1,), neg, jnp.int32), strand_seen[:-1]])
        placed_any = (group_count - outs["unsched"]) > 0
        prio_inv = placed_any & (gp < strand_before)
        aux = aux + [prio_inv.astype(jnp.float32)]               # G
    packed = jnp.concatenate(head + mid + [
        outs["unsched"].astype(jnp.float32).reshape(-1),     # G
        outs["dom_placed"].astype(jnp.float32).reshape(-1),  # G*D
        final["used"].reshape(-1),                            # N*R
        final["node_pool"].astype(jnp.float32),               # N
        final["node_zone"].astype(jnp.float32),               # N
        final["node_ct"].astype(jnp.float32),                 # N
        final["num_active"][None].astype(jnp.float32),        # 1
    ] + aux)
    return packed


solve_ffd = partial(jax.jit, static_argnames=(
    "max_nodes", "zc", "with_topology", "sparse_k", "sparse_n",
    "mask_packed", "explain", "with_gang",
    "with_priority"))(_solve_ffd_impl)


def pack_problem(prob):
    """Coalesce the per-problem arrays into ONE uint8 buffer + a static
    layout. Fifteen small host->device transfers pay fifteen fixed link
    costs on the device tunnel; one contiguous buffer pays one (the
    dominant share of a small solve's latency there — config1 measured
    ~74 ms of fixed overhead on 2 ms of work).  4-byte dtypes stay
    4-aligned because the byte-wide arrays (packed masks, bools) are
    emitted last.  Returns (buf, layout): layout is a hashable tuple of
    (position, shape, dtype-name) in emission order for the jit cache."""
    import numpy as np
    order = sorted(range(len(prob)),
                   key=lambda i: prob[i].dtype.itemsize != 4)
    chunks, layout = [], []
    for i in order:
        a = np.ascontiguousarray(prob[i])
        # _unpack_problem knows exactly these dtypes; anything else (a
        # stray float64 from numpy defaults) would silently shift every
        # later offset and corrupt the solve — fail loudly instead
        assert a.dtype.name in ("float32", "int32", "uint8", "bool"), \
            (i, a.dtype)
        layout.append((i, a.shape, a.dtype.name))
        chunks.append(a.view(np.uint8).reshape(-1))
    return np.concatenate(chunks), tuple(
        (i, tuple(s), d) for i, s, d in layout)


def _unpack_problem(buf, layout):
    """Device-side inverse of pack_problem: slice + bitcast per array
    (all offsets/shapes static, so XLA sees plain reshapes)."""
    out = [None] * len(layout)
    off = 0
    for i, shape, dtype in layout:
        n = 1
        for s in shape:
            n *= s
        if dtype in ("float32", "int32"):
            raw = jax.lax.bitcast_convert_type(
                buf[off:off + 4 * n].reshape(-1, 4),
                jnp.float32 if dtype == "float32" else jnp.int32)
            out[i] = raw.reshape(shape)
            off += 4 * n
        else:  # uint8 / bool
            raw = buf[off:off + n]
            out[i] = (raw.astype(bool) if dtype == "bool"
                      else raw).reshape(shape)
            off += n
    return tuple(out)


def _solve_ffd_coalesced_impl(buf, col_alloc, col_daemon, pt_alloc,
                              col_pool, pool_daemon, col_zone, col_ct,
                              layout=None, max_nodes: int = 1024,
                              zc: int = 1, with_topology: bool = True,
                              sparse_k: int = 0, sparse_n: int = 0,
                              mask_packed: bool = False,
                              explain: int = 0, with_gang: int = 0,
                              with_priority: int = 0):
    """solve_ffd fed from one coalesced problem buffer (see
    pack_problem).  Catalog args stay separate — they are
    device-resident across solves and never travel.  with_priority
    implies the buffer carries the group_prio row as slot 17 —
    priority-free problems keep the exact 17-slot pre-priority layout
    (and therefore the exact pre-priority program)."""
    parts = _unpack_problem(buf, layout)
    (group_req, group_count, group_mask, exist_cap, exist_remaining,
     pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
     group_skew, group_mindom, group_delig, group_whole, group_gang,
     exist_zone, exist_ct) = parts[:17]
    group_prio = parts[17] if with_priority else None
    return _solve_ffd_impl(
        group_req, group_count, group_mask, exist_cap, exist_remaining,
        col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon,
        pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
        group_skew, group_mindom, group_delig, group_whole, group_gang,
        col_zone, col_ct, exist_zone, exist_ct, group_prio=group_prio,
        max_nodes=max_nodes, zc=zc, with_topology=with_topology,
        sparse_k=sparse_k, sparse_n=sparse_n, mask_packed=mask_packed,
        explain=explain, with_gang=with_gang,
        with_priority=with_priority)


_COALESCED_STATICS = ("layout", "max_nodes", "zc", "with_topology",
                      "sparse_k", "sparse_n", "mask_packed", "explain",
                      "with_gang", "with_priority")
solve_ffd_coalesced = partial(
    jax.jit, static_argnames=_COALESCED_STATICS)(_solve_ffd_coalesced_impl)
# The pipelined executor's variant: the problem buffer (arg 0) is DONATED
# — the executing program may reuse its bytes for outputs, so the upload
# slot it came from is dead the moment this dispatches (reuse raises; see
# pipeline.DeviceSlots for the two-slot rotation that makes the next
# upload land in fresh memory while this program is still running).
solve_ffd_coalesced_donated = partial(
    jax.jit, static_argnames=_COALESCED_STATICS,
    donate_argnums=(0,))(_solve_ffd_coalesced_impl)


def _solve_ffd_resident_impl(buf, mask_table, col_alloc, col_daemon,
                             pt_alloc, col_pool, pool_daemon, col_zone,
                             col_ct, layout=None, max_nodes: int = 1024,
                             zc: int = 1, sparse_n: int = 0,
                             axis_name=None, explain: int = 0,
                             with_gang: int = 0, with_priority: int = 0):
    """The mesh executor's kernel body (parallel/mesh.py wraps this in
    `shard_map` + jit): one coalesced REPLICATED problem buffer, the
    device-RESIDENT sharded catalog args, and a device-resident sharded
    mask-row table.  The buffer's position 2 carries per-group row
    indices into `mask_table` instead of the [G, O] mask itself — the
    mask rows are content-addressed and resident across solves
    (solve.py _MaskRowRegistry), so no O-axis array travels per solve.
    The row gather runs on each device's local [C, O/devices] shard."""
    parts = _unpack_problem(buf, layout)
    (group_req, group_count, group_rows, exist_cap, exist_remaining,
     pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
     group_skew, group_mindom, group_delig, group_whole, group_gang,
     exist_zone, exist_ct) = parts[:17]
    group_prio = parts[17] if with_priority else None
    group_mask = mask_table[group_rows]
    return _solve_ffd_impl(
        group_req, group_count, group_mask, exist_cap, exist_remaining,
        col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon,
        pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
        group_skew, group_mindom, group_delig, group_whole, group_gang,
        col_zone, col_ct, exist_zone, exist_ct, group_prio=group_prio,
        max_nodes=max_nodes, zc=zc, sparse_n=sparse_n,
        axis_name=axis_name, explain=explain, with_gang=with_gang,
        with_priority=with_priority)

def _solve_ffd_delta_impl(buf, col_alloc, col_daemon, pt_alloc, col_pool,
                          pool_daemon, col_zone, col_ct, layout=None,
                          max_nodes: int = 1024, zc: int = 1,
                          sparse_n: int = 0, mask_packed: bool = False,
                          seed_packed: bool = False, explain: int = 0,
                          with_gang: int = 0, with_priority: int = 0):
    """The delta path's seeded kernel (single-device): one coalesced
    buffer carrying the restricted SUFFIX problem (the changed groups
    only) PLUS the prefix seed state — used/pool/active for the node
    slots a previous solve's unchanged prefix opened, and their
    surviving-column masks.  exist_remaining arrives pre-consumed by the
    prefix (host replay in solver/delta.py mirrors the kernel's own
    arithmetic op-for-op, so the seeded scan is bit-identical to the
    full solve's suffix steps).  Topology-free by contract — the delta
    path falls back to a full solve for anything else — so the heavy
    branch is never traced (with_topology=False)."""
    (group_req, group_count, group_mask, exist_cap, exist_remaining,
     pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
     group_skew, group_mindom, group_delig, group_whole, group_gang,
     exist_zone, exist_ct, seed_used, seed_pool, seed_active,
     seed_colmask) = _unpack_problem(buf, layout)
    if seed_packed:
        seed_colmask = _expand_packed_mask(seed_colmask,
                                           col_alloc.shape[0])
    return _solve_ffd_impl(
        group_req, group_count, group_mask, exist_cap, exist_remaining,
        col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon,
        pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
        group_skew, group_mindom, group_delig, group_whole, group_gang,
        col_zone, col_ct, exist_zone, exist_ct,
        seed_used=seed_used, seed_colmask=seed_colmask,
        seed_pool=seed_pool, seed_active=seed_active,
        max_nodes=max_nodes, zc=zc, with_topology=False,
        sparse_n=sparse_n, mask_packed=mask_packed, explain=explain,
        with_gang=with_gang, with_priority=with_priority)


_DELTA_STATICS = ("layout", "max_nodes", "zc", "sparse_n", "mask_packed",
                  "seed_packed", "explain", "with_gang", "with_priority")
solve_ffd_delta = partial(
    jax.jit, static_argnames=_DELTA_STATICS)(_solve_ffd_delta_impl)


def _solve_ffd_delta_resident_impl(buf, seed_colmask, mask_table,
                                   col_alloc, col_daemon, pt_alloc,
                                   col_pool, pool_daemon, col_zone,
                                   col_ct, layout=None,
                                   max_nodes: int = 1024, zc: int = 1,
                                   axis_name=None, explain: int = 0,
                                   with_gang: int = 0,
                                   with_priority: int = 0):
    """Mesh variant of the delta kernel (parallel/mesh.py wraps it in
    shard_map): the suffix problem's slot 2 carries row indices into the
    resident mask table (exactly like _solve_ffd_resident_impl), and the
    seed column masks arrive as a separate column-sharded operand — the
    one per-delta-solve O-axis transfer, logged by the executor so the
    residency accounting stays honest."""
    (group_req, group_count, group_rows, exist_cap, exist_remaining,
     pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
     group_skew, group_mindom, group_delig, group_whole, group_gang,
     exist_zone, exist_ct, seed_used, seed_pool,
     seed_active) = _unpack_problem(buf, layout)
    group_mask = mask_table[group_rows]
    return _solve_ffd_impl(
        group_req, group_count, group_mask, exist_cap, exist_remaining,
        col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon,
        pool_limit, group_ncap, group_dsel, group_dbase, group_dcap,
        group_skew, group_mindom, group_delig, group_whole, group_gang,
        col_zone, col_ct, exist_zone, exist_ct,
        seed_used=seed_used, seed_colmask=seed_colmask,
        seed_pool=seed_pool, seed_active=seed_active,
        max_nodes=max_nodes, zc=zc, with_topology=False,
        axis_name=axis_name, explain=explain, with_gang=with_gang,
        with_priority=with_priority)


# The consolidation simulator's batch axis (SURVEY §7 step 6): many
# candidate-removal simulations against one cluster state share the catalog
# (columns replicated) while per-candidate pods/existing/limits vmap over
# the leading axis — one device call evaluates the whole candidate set.
_BATCH_AXES = (0, 0, 0, 0, 0,          # group_req..exist_remaining
               None, None, None,        # col_alloc, col_daemon, pt_alloc
               None, None,              # col_pool, pool_daemon (shared)
               0,                       # pool_limit
               0, 0, 0, 0, 0, 0, 0, 0, 0,  # topology group arrays
                                        # (+whole +gang)
               None, None,              # col_zone, col_ct (shared)
               0, 0)                    # exist_zone, exist_ct

def _solve_ffd_batch_impl(*args, max_nodes: int = 1024, zc: int = 1,
                          sparse_k: int = 0, sparse_n: int = 0,
                          mask_packed: bool = False, explain: int = 0,
                          with_gang: int = 0, with_priority: int = 0):
    # explain is armed (counts) only for UNCAPPED batches — the fused
    # solverd lane's real provisioning requests; capped consolidation
    # sims keep explain=0 (counterfactuals must not pay or pollute)
    # with_priority rides as a 25th positional operand (stacked [B, G]
    # group_prio, batch axis 0) — absent entirely for priority-free
    # batches, so their arg list and program match the pre-priority lane
    axes = _BATCH_AXES + ((0,) if len(args) > len(_BATCH_AXES) else ())
    return jax.vmap(partial(_solve_ffd_impl, max_nodes=max_nodes, zc=zc,
                            sparse_k=sparse_k, sparse_n=sparse_n,
                            mask_packed=mask_packed,
                            explain=min(explain, 1),
                            with_gang=with_gang,
                            with_priority=with_priority),
                    in_axes=axes)(*args)


_BATCH_STATICS = ("max_nodes", "zc", "sparse_k", "sparse_n",
                  "mask_packed", "explain", "with_gang", "with_priority")
solve_ffd_batch = partial(
    jax.jit, static_argnames=_BATCH_STATICS)(_solve_ffd_batch_impl)
# pipelined variant: the per-problem stacked tensors (batch axis 0 in
# _BATCH_AXES) are donated — they are rebuilt per chunk anyway, and
# donation lets chunk i's outputs reuse chunk i's input memory while
# chunk i+1's upload allocates fresh (the double-buffer invariant).
# Catalog tensors (axis None) replicate across solves and must survive.
solve_ffd_batch_donated = partial(
    jax.jit, static_argnames=_BATCH_STATICS,
    donate_argnums=tuple(
        i for i, ax in enumerate(_BATCH_AXES) if ax == 0))(
            _solve_ffd_batch_impl)


_BIG = 2 ** 29  # mirrors encode.BIG (no import: encode must stay jax-free)


def _solve_ffd_sweep_impl(
    # per-simulation (vmapped axis 0)
    group_req,      # [B, G, R]
    group_count,    # [B, G]
    group_class,    # [B, G] i32 — row into the class tables
    exclude_idx,    # [B, X] i32 — union rows this sim removes (-1 = pad)
    price_cap,      # [B] f32 — +inf when uncapped
    pool_limit,     # [B, P, R]
    # shared across the batch (replicated)
    class_mask,     # [C, O] bool — per-class catalog column mask
    class_cap,      # [C, E] i32 — per-class per-union-node allowance
    exist_remaining,  # [E, R]
    exist_zone,     # [E] i32
    exist_ct,       # [E] i32
    col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon,
    col_price,      # [O] f32
    col_zone, col_ct,
    max_nodes: int = 8, zc: int = 1, sparse_k: int = 0,
    mask_packed: bool = False,
):
    """The consolidation-sweep kernel: every simulation is 'the shared
    cluster snapshot minus a few candidate nodes' (SURVEY §3.3 hot loop
    #2), so the batch axis carries only (pod groups, exclusion indices,
    price cap) — the snapshot's node tensors and the per-class column
    masks upload once and are indexed on device. This removes the
    per-simulation host encode/stack of [E,*] arrays that dominated the
    generic batched path (profiled ~85% of the config4 sweep).

    Topology-inactive by construction: the caller routes any simulation
    with spread/affinity activity through the generic path, so the
    domain tensors are zeros and every group takes the kernel's light
    branch.
    """
    E = exist_remaining.shape[0]
    if mask_packed:
        # shared [C, ceil(O/8)] -> [C, O] once per call (the per-sim
        # masks are class_mask rows, so one expansion serves the batch)
        class_mask = _expand_packed_mask(class_mask, col_price.shape[0])

    def one(greq, gcount, gcls, excl, pcap, plim):
        keep = jnp.all(
            jnp.arange(E, dtype=jnp.int32)[None, :] != excl[:, None],
            axis=0)                                             # [E]
        er = exist_remaining * keep[:, None]
        ecap = class_cap[gcls] * keep[None, :].astype(class_cap.dtype)
        gmask = class_mask[gcls] & (col_price < pcap)[None, :]
        G = greq.shape[0]
        zG = jnp.zeros((G,), jnp.int32)
        zGD = jnp.zeros((G, 1), jnp.int32)
        return _solve_ffd_impl(
            greq, gcount, gmask, ecap, er,
            col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon, plim,
            jnp.full((G,), _BIG, jnp.int32),   # ncap (no hostname caps)
            zG,                                 # dsel = none
            zGD,                                # dbase
            jnp.full((G, 1), _BIG, jnp.int32),  # dcap
            jnp.full((G,), _BIG, jnp.int32),    # skew (unbounded)
            zG,                                 # mindom
            jnp.zeros((G, 1), bool),            # delig
            jnp.zeros((G,), bool),              # whole (sweep holes coloc)
            jnp.zeros((G,), bool),              # gang (sweep holes gangs)
            col_zone, col_ct, exist_zone, exist_ct,
            max_nodes=max_nodes, zc=zc, with_topology=False,
            sparse_k=sparse_k)

    return jax.vmap(one)(group_req, group_count, group_class,
                         exclude_idx, price_cap, pool_limit)


_SWEEP_STATICS = ("max_nodes", "zc", "sparse_k", "mask_packed")
solve_ffd_sweep = partial(
    jax.jit, static_argnames=_SWEEP_STATICS)(_solve_ffd_sweep_impl)
# pipelined variant: per-simulation tensors (args 0-5) donate; the shared
# snapshot/class tables replicate across chunks and must survive
solve_ffd_sweep_donated = partial(
    jax.jit, static_argnames=_SWEEP_STATICS,
    donate_argnums=tuple(range(6)))(_solve_ffd_sweep_impl)


def _solve_ffd_sweep_topo_impl(
    # per-simulation (vmapped axis 0)
    group_req,      # [B, G, R]
    group_count,    # [B, G]
    group_class,    # [B, G] i32 — row into the class tables
    exclude_idx,    # [B, X] i32 — union rows this sim removes (-1 = pad)
    price_cap,      # [B] f32 — +inf when uncapped
    pool_limit,     # [B, P, R]
    group_ncap,     # [B, G] i32
    group_dsel,     # [B, G] i32
    group_dbase,    # [B, G, D] i32
    group_dcap,     # [B, G, D] i32
    group_skew,     # [B, G] i32
    group_mindom,   # [B, G] i32
    group_delig,    # [B, G, D] bool
    # shared across the batch (replicated)
    class_mask,     # [C, O] bool
    class_cap,      # [C, E] i32 — hostname clamps folded in at build time
    exist_remaining, exist_zone, exist_ct,
    col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon,
    col_price, col_zone, col_ct,
    max_nodes: int = 8, zc: int = 1, sparse_k: int = 0,
    mask_packed: bool = False,
):
    """The sweep kernel's HEAVY lane: same shared-snapshot batching as
    solve_ffd_sweep, but with real per-simulation topology tensors
    (dynamic zone/ct spread + anti, hostname caps via pre-clamped
    class_cap) and the domain branch TRACED (with_topology=True).  A
    separate jit entry so constraint-light sweeps never pay this
    branch's compile time (the two lanes cache independently)."""
    E = exist_remaining.shape[0]
    if mask_packed:
        class_mask = _expand_packed_mask(class_mask, col_price.shape[0])

    def one(greq, gcount, gcls, excl, pcap, plim,
            ncap, dsel, dbase, dcap, skew, mindom, delig):
        keep = jnp.all(
            jnp.arange(E, dtype=jnp.int32)[None, :] != excl[:, None],
            axis=0)                                             # [E]
        er = exist_remaining * keep[:, None]
        ecap = class_cap[gcls] * keep[None, :].astype(class_cap.dtype)
        gmask = class_mask[gcls] & (col_price < pcap)[None, :]
        return _solve_ffd_impl(
            greq, gcount, gmask, ecap, er,
            col_alloc, col_daemon, pt_alloc, col_pool, pool_daemon, plim,
            ncap, dsel, dbase, dcap, skew, mindom, delig,
            jnp.zeros(greq.shape[:1], bool),    # whole (sweep holes coloc)
            jnp.zeros(greq.shape[:1], bool),    # gang (sweep holes gangs)
            col_zone, col_ct, exist_zone, exist_ct,
            max_nodes=max_nodes, zc=zc, with_topology=True,
            sparse_k=sparse_k)

    return jax.vmap(one)(group_req, group_count, group_class,
                         exclude_idx, price_cap, pool_limit,
                         group_ncap, group_dsel, group_dbase, group_dcap,
                         group_skew, group_mindom, group_delig)


solve_ffd_sweep_topo = partial(
    jax.jit, static_argnames=_SWEEP_STATICS)(_solve_ffd_sweep_topo_impl)
# pipelined variant: per-simulation tensors (args 0-12, incl. the
# per-sim topology rows) donate
solve_ffd_sweep_topo_donated = partial(
    jax.jit, static_argnames=_SWEEP_STATICS,
    donate_argnums=tuple(range(13)))(_solve_ffd_sweep_topo_impl)


def unpack(packed, G: int, E: int, N: int, RDIM: int, D: int,
           sparse_k: int = 0, sparse_n: int = 0, explain: int = 0,
           explain_o: int = 0, with_priority: int = 0):
    """Split the flat result buffer back into named host arrays.  With
    sparse_k > 0 the buffer's head carries top-K (count, index) pairs per
    group (see _solve_ffd_impl) and the dense [G, E] take_exist row is
    rebuilt here by scatter — top_k indices are distinct per row, so the
    scatter is collision-free and lossless when K bounds the group size.
    sparse_n > 0 rebuilds take_new the same way; its K is only a
    warm-start estimate, so the kernel's per-group nonzero-count row is
    checked here and ``new_overflow`` reports a lossy compaction (the
    caller re-runs dense).  explain > 0 parses the provenance aux tail
    (``explain_counts`` [G, EXPLAIN_C] + ``explain_bits`` [G]; explain
    >= 2 also ``explain_map`` [G, explain_o]) — the tail is purely
    additive, so an explain-armed buffer unpacks fine without these
    parameters (the aux simply stays unread)."""
    import numpy as np
    # writable host array: device buffers surface as read-only views, and
    # the topology repair pass (solve.py) mutates these arrays in place.
    # An already-writable numpy input (a batch row the caller pulled) is
    # used as-is — per-sim arrays are disjoint slices, so in-place repair
    # on the view never aliases another sim's decode.
    flat = np.asarray(packed)
    if not flat.flags.writeable:
        flat = np.array(flat)
    K = sparse_k
    Kn = sparse_n
    head = 2 * G * K if K else G * E
    mid = (2 * G * Kn + G) if Kn else G * N
    sizes = [head, mid, G, G * D, N * RDIM, N, N, N, 1]
    offs = np.cumsum([0] + sizes)
    if K:
        cnt = flat[offs[0]:offs[0] + G * K].reshape(G, K)
        idx = flat[offs[0] + G * K:offs[1]].reshape(G, K).astype(np.int64)
        take_exist = np.zeros((G, E), dtype=flat.dtype)
        # mask the empty slots: they carry (cnt=0, idx=0) and an
        # unmasked scatter would zero a genuine entry at column 0
        m = cnt > 0
        take_exist[np.nonzero(m)[0], idx[m]] = cnt[m]
    else:
        take_exist = flat[offs[0]:offs[1]].reshape(G, E)
    new_overflow = False
    if Kn:
        cntn = flat[offs[1]:offs[1] + G * Kn].reshape(G, Kn)
        idxn = flat[offs[1] + G * Kn:
                    offs[1] + 2 * G * Kn].reshape(G, Kn).astype(np.int64)
        nnz = flat[offs[1] + 2 * G * Kn:offs[2]]
        new_overflow = bool((nnz > Kn).any())
        take_new = np.zeros((G, N), dtype=flat.dtype)
        mn_ = cntn > 0
        take_new[np.nonzero(mn_)[0], idxn[mn_]] = cntn[mn_]
    else:
        take_new = flat[offs[1]:offs[2]].reshape(G, N)
    out = dict(
        take_exist=take_exist,
        take_new=take_new,
        new_overflow=new_overflow,
        unsched=flat[offs[2]:offs[3]],
        dom_placed=flat[offs[3]:offs[4]].reshape(G, D),
        used=flat[offs[4]:offs[5]].reshape(N, RDIM),
        node_pool=flat[offs[5]:offs[6]].astype(np.int32),
        node_zone=flat[offs[6]:offs[7]].astype(np.int32),
        node_ct=flat[offs[7]:offs[8]].astype(np.int32),
        num_active=flat[offs[8]],
    )
    off = int(offs[-1])
    if explain:
        C = EXPLAIN_C
        out["explain_counts"] = \
            flat[off:off + G * C].reshape(G, C).astype(np.int64)
        off += G * C
        out["explain_bits"] = flat[off:off + G].astype(np.int64)
        off += G
        if explain >= 2 and explain_o:
            out["explain_map"] = flat[off:off + G * explain_o] \
                .reshape(G, explain_o).astype(np.int8)
            off += G * explain_o
    if with_priority:
        # the kernel's inversion witness (ISSUE 16): last additive aux
        # row, after any explain aux — True for a group that placed
        # while an earlier (higher-priority) group had already stranded
        out["prio_inv"] = flat[off:off + G] > 0.5
    return out
