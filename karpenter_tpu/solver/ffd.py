"""The jitted grouped-FFD kernel.

One `lax.scan` step consumes one pod equivalence class (already in FFD
order) and performs three vectorized fills, mirroring the oracle's
existing → in-flight → open-new cascade exactly but over whole groups:

  1. existing nodes: per-node pod capacity via elementwise floor-division,
     greedy prefix fill in node order (= sequential first-fit for identical
     pods)
  2. in-flight nodes: per-(node × column) capacity, max over each node's
     surviving columns, prefix fill; survivors' column masks AND-ed with the
     group's compatibility row
  3. open new nodes: best pods-per-node over feasible columns of the
     highest-priority compatible pool, ceil-divide to get node count,
     activate slots

Everything is static-shaped (`G × E × O × N` padded to buckets by the
caller); control flow is masked arithmetic, no data-dependent branching —
the whole solve is one XLA program (SURVEY §7: compiler-friendly control
flow, no recompiles inside the latency budget).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

EPS = 1e-3


def _fit_count(avail: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """How many pods of per-pod request `req` [R] fit in `avail` [..., R]."""
    safe = jnp.where(req > 0, req, 1.0)
    counts = jnp.floor((avail + EPS) / safe)
    counts = jnp.where(req > 0, counts, jnp.float32(2**30))
    c = jnp.min(counts, axis=-1)
    return jnp.clip(c, 0, 2**30).astype(jnp.int32)


def _prefix_fill(cap: jnp.ndarray, want: jnp.ndarray) -> jnp.ndarray:
    """Greedy fill in index order: take as much as each slot holds until
    `want` is exhausted — identical to sequential first-fit for
    interchangeable pods."""
    cum = jnp.cumsum(cap)
    before = cum - cap
    return jnp.clip(jnp.minimum(cap, want - before), 0, None)


@partial(jax.jit, static_argnames=("max_nodes",))
def solve_ffd(
    group_req: jnp.ndarray,       # [G, R]
    group_count: jnp.ndarray,     # [G]
    group_mask: jnp.ndarray,      # [G, O] bool
    exist_mask: jnp.ndarray,      # [G, E] bool
    exist_remaining: jnp.ndarray, # [E, R]
    col_alloc: jnp.ndarray,       # [O, R]
    col_daemon: jnp.ndarray,      # [O, R]
    col_pool: jnp.ndarray,        # [O] i32
    pool_daemon: jnp.ndarray,     # [P, R]
    pool_limit: jnp.ndarray,      # [P, R]
    max_nodes: int = 1024,
):
    G, RDIM = group_req.shape
    E = exist_remaining.shape[0]
    O = col_alloc.shape[0]
    P = pool_limit.shape[0]
    N = max_nodes

    init = dict(
        exist_rem=exist_remaining,
        used=jnp.zeros((N, RDIM), jnp.float32),
        colmask=jnp.zeros((N, O), bool),
        active=jnp.zeros((N,), bool),
        node_pool=jnp.zeros((N,), jnp.int32),
        num_active=jnp.int32(0),
        limits=pool_limit,
    )

    def step(carry, xs):
        req, cnt, gmask, emask = xs
        exist_rem = carry["exist_rem"]
        used = carry["used"]
        colmask = carry["colmask"]
        active = carry["active"]
        node_pool = carry["node_pool"]
        num_active = carry["num_active"]
        limits = carry["limits"]

        # -- 1. existing nodes ------------------------------------------
        cap_e = jnp.where(emask, _fit_count(exist_rem, req), 0) if E else jnp.zeros((0,), jnp.int32)
        take_e = _prefix_fill(cap_e, cnt) if E else cap_e
        exist_rem = exist_rem - take_e[:, None] * req if E else exist_rem
        c1 = cnt - (take_e.sum() if E else 0)

        # -- 2. in-flight nodes -----------------------------------------
        avail = col_alloc[None, :, :] - used[:, None, :]           # [N,O,R]
        cap_no = _fit_count(avail, req)                            # [N,O]
        cap_no = jnp.where(colmask & gmask[None, :], cap_no, 0)
        cap_n = jnp.where(active, cap_no.max(axis=1), 0)
        # pool limits are COLLECTIVE: clamp each node's cap by what the
        # pool's budget leaves after earlier (lower-index) nodes of the same
        # pool take theirs — per-node clamping alone would let several nodes
        # of one pool jointly overrun the limit (P is static → unrolled)
        limit_cap = _fit_count(limits, req)                        # [P]
        for p in range(P):
            mask_p = node_pool == p
            cap_p = jnp.where(mask_p, cap_n, 0)
            before_p = jnp.cumsum(cap_p) - cap_p
            allowed = jnp.clip(limit_cap[p] - before_p, 0, None)
            cap_n = jnp.where(mask_p, jnp.minimum(cap_p, allowed), cap_n)
        take_n = _prefix_fill(cap_n, c1)
        used = used + take_n[:, None] * req
        touched = take_n > 0
        colmask = jnp.where(touched[:, None], colmask & gmask[None, :], colmask)
        col_ok = jnp.all(col_alloc[None, :, :] - used[:, None, :] >= -EPS, axis=-1)
        colmask = colmask & col_ok
        pool_take = jax.ops.segment_sum(take_n.astype(jnp.float32), node_pool,
                                        num_segments=P)
        limits = limits - pool_take[:, None] * req
        c2 = c1 - take_n.sum()

        # -- 3. open new nodes ------------------------------------------
        # Unrolled over pools in priority order (P is static): a pool whose
        # limit or catalog can't absorb the remaining pods falls through to
        # the next pool, exactly like the oracle's per-pod pool cascade.
        per_col = _fit_count(col_alloc - col_daemon, req)          # [O]
        col_feas = gmask & (per_col >= 1)
        idx = jnp.arange(N, dtype=jnp.int32)
        c_rem = c2
        k_new_total = jnp.zeros((N,), jnp.int32)
        for p in range(P):
            cols_p = col_feas & (col_pool == p)
            k_full = jnp.max(jnp.where(cols_p, per_col, 0))
            pool_room = jnp.all(limits[p] - pool_daemon[p] - req >= -EPS)
            can = cols_p.any() & pool_room & (c_rem > 0) & (k_full > 0)
            m_need = jnp.where(can, -(-c_rem // jnp.maximum(k_full, 1)), 0)
            # per-node charge against the pool limit (full-node approximation)
            charge = pool_daemon[p] + k_full.astype(jnp.float32) * req
            m_limit = _fit_count(limits[p][None, :], charge)[0]
            m = jnp.minimum(jnp.minimum(m_need, m_limit), N - num_active)
            newmask = (idx >= num_active) & (idx < num_active + m)
            pos = idx - num_active
            taken_new = jnp.minimum(c_rem, m * k_full)
            k_node = jnp.where(
                newmask,
                jnp.where(pos == m - 1, taken_new - (m - 1) * k_full, k_full),
                0)
            new_used = pool_daemon[p][None, :] + k_node[:, None].astype(jnp.float32) * req
            used = jnp.where(newmask[:, None], new_used, used)
            new_colmask = cols_p[None, :] & jnp.all(
                col_alloc[None, :, :] - new_used[:, None, :] >= -EPS, axis=-1)
            colmask = jnp.where(newmask[:, None], new_colmask, colmask)
            active = active | newmask
            node_pool = jnp.where(newmask, jnp.int32(p), node_pool)
            num_active = num_active + m
            limits = limits.at[p].add(
                -(m.astype(jnp.float32) * pool_daemon[p]
                  + taken_new.astype(jnp.float32) * req))
            k_new_total = k_new_total + k_node
            c_rem = c_rem - taken_new
        unsched = c_rem

        carry = dict(exist_rem=exist_rem, used=used, colmask=colmask,
                     active=active, node_pool=node_pool,
                     num_active=num_active, limits=limits)
        out = dict(take_exist=take_e, take_new=take_n + k_new_total,
                   unsched=unsched)
        return carry, out

    xs = (group_req, group_count, group_mask, exist_mask)
    final, outs = jax.lax.scan(step, init, xs)
    # Results are packed into ONE flat f32 buffer: each host pull pays a
    # full round trip on the device link, so six small arrays cost six RTTs
    # — one concatenated buffer costs one. colmask [N,O] stays on device
    # entirely; the host reconstructs it from (take_new, used, group_mask).
    packed = jnp.concatenate([
        outs["take_exist"].astype(jnp.float32).reshape(-1),  # G*E
        outs["take_new"].astype(jnp.float32).reshape(-1),    # G*N
        outs["unsched"].astype(jnp.float32).reshape(-1),     # G
        final["used"].reshape(-1),                            # N*R
        final["node_pool"].astype(jnp.float32),               # N
        final["num_active"][None].astype(jnp.float32),        # 1
    ])
    return packed


def unpack(packed, G: int, E: int, N: int, RDIM: int):
    """Split the flat result buffer back into named host arrays."""
    import numpy as np
    flat = np.asarray(packed)
    sizes = [G * E, G * N, G, N * RDIM, N, 1]
    offs = np.cumsum([0] + sizes)
    return dict(
        take_exist=flat[offs[0]:offs[1]].reshape(G, E),
        take_new=flat[offs[1]:offs[2]].reshape(G, N),
        unsched=flat[offs[2]:offs[3]],
        used=flat[offs[3]:offs[4]].reshape(N, RDIM),
        node_pool=flat[offs[4]:offs[5]].astype(np.int32),
        num_active=flat[offs[5]],
    )
