"""Placement provenance: the reason-code registry and the explain engine.

Karpenter's operator surface is *decision* observability — for every
unschedulable pod it names the exact constraint that eliminated every
candidate.  This module is that layer for the reproduction, and it is the
ONE enum owner for every structured verdict in the tree:

  * **Reason codes** (`REGISTRY`): every `res.unschedulable[...]`
    assignment — kernel strands, oracle verdicts, decode-time claim-shape
    violations — emits a `Reason` (a `str` subclass, so the legacy
    human-readable string stays intact for logs and existing assertions)
    carrying a registered `.code` and an optional `.tree` (the per-group
    constraint-elimination breakdown).  Cross-component discrimination is
    a code comparison, never a substring match (the `solve.py:571`
    hazard this module retires).
  * **Constraint classes** (`CONSTRAINTS`): the canonical
    per-constraint elimination vocabulary.  The device kernel computes
    the `KERNEL_CONSTRAINTS` subset as auxiliary outputs
    (`ffd._solve_ffd_impl(explain=...)`, per-group counts + reason
    bitsets); the host encode path owns `HOST_CONSTRAINTS` (label/taint
    compatibility and the price cap, which is folded into the group mask
    before the kernel ever sees it).
  * **Delta-fallback and shed reasons**: the delta seam's fallback
    vocabulary and the tenant scheduler's shed reasons are registered
    here too, so no component grows a private reason namespace.
  * **Explain engine**: `build_tree` turns (encoding, kernel output,
    group) into a per-pod reason tree — which constraint eliminated
    which catalog columns, the nearest-miss instance type and by how
    much, and what change (limit raise, price-cap raise, capacity) would
    unblock it.  `host_counts` is the numpy fallback used when kernel
    aux is absent (batched/sweep paths, replay of old captures).
  * **ExplainStore**: a bounded per-process ring of per-pod explain
    entries, fed by the provisioning controller's verdict application
    and served by `GET /debug/explain?pod=&trace_id=`.

Gate: ``KARPENTER_TPU_EXPLAIN=off|counts|full`` (default **counts**).
`counts` adds the cheap per-group aux outputs to the kernel (budgeted
<1% of the headline p50, `bench.py --explain`); `full` additionally
materializes the [G, O] per-column elimination-class map — replay /
post-mortem territory, not the steady-state default.

This module is deliberately jax-free: the oracle, the cluster event
plumbing, and the lint tooling import it without paying a jax import
(the package `__init__` resolves the solver itself lazily for the same
reason).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

# -- the fit-slack epsilon (one owner, ISSUE 18) ---------------------------
# Every fit test in the solver — the kernel's `floor((avail + EPS)/req)`
# and `>= -EPS` subtract-compares, the host recheck rows, the delta
# seed's whole-group verdicts — uses THIS slack.  It lives here (the
# solver's jax-free vocabulary module) so the kernel (ffd), the encoder,
# and the host paths can all import the one spelling; the
# one-owner-constant rule flags any re-literal'd twin.
EPS = 1e-3

# -- constraint classes (canonical order) ---------------------------------
# The elimination vocabulary: why a catalog column cannot take a pod of
# this group.  Order is a wire contract — the kernel's aux counts rows
# use KERNEL_CONSTRAINTS order (ffd.py imports these), and the reason
# bitset's bit i is KERNEL_CONSTRAINTS[i].
HOST_CONSTRAINTS = ("compat", "price")
KERNEL_CONSTRAINTS = ("fit", "limit", "topology", "whole_node", "slots")
# "gang" classifies the atomic multi-node verdicts (ISSUE 15).  It is
# NOT a kernel aux class: the kernel attributes a gang's atomic failure
# to the existing whole_node class (the gang fill IS the whole-node
# fill's K-node generalization), keeping the aux row width — and every
# recorded delta prefix — stable; the gang-specific discrimination
# lives in the reason CODES below and their per-gang trees.
# "priority" classifies the band/preemption verdicts (ISSUE 16) — like
# "gang", NOT a kernel aux class: the kernel's priority aux row is a
# witness bit (an inversion gate), not an elimination count, so the aux
# row width and every recorded delta prefix stay stable.
CONSTRAINTS = HOST_CONSTRAINTS + KERNEL_CONSTRAINTS + ("gang", "priority")

_CONSTRAINT_HELP = {
    "compat": "label/taint/requirement incompatibility (host encode mask)",
    "price": "price cap eliminated the column (host encode mask)",
    "fit": "resource request does not fit an empty node of the column",
    "limit": "the nodepool's remaining limit cannot fund one more pod",
    "topology": "the column's domain is ineligible or at its skew ceiling",
    "whole_node": "no single node could hold the whole co-located group",
    "slots": "the solver's node-slot axis was exhausted",
    "gang": "the gang's all-or-nothing, single-domain placement failed",
    "priority": "priority-band packing or preemption planning decided "
                "the outcome",
}


# -- reason codes ----------------------------------------------------------
class ReasonSpec:
    __slots__ = ("code", "constraint", "summary")

    def __init__(self, code: str, constraint: str, summary: str):
        assert constraint in CONSTRAINTS + ("none",), constraint
        self.code = code
        self.constraint = constraint
        self.summary = summary


REGISTRY: Dict[str, ReasonSpec] = {}


def _register(code: str, constraint: str, summary: str) -> str:
    REGISTRY[code] = ReasonSpec(code, constraint, summary)
    return code


# kernel strands (solver/solve.py _unsched_reason + decode)
NO_NODEPOOL = _register(
    "NoNodepoolCompatible", "compat",
    "no nodepool's template/taints/types are compatible with the pod")
TOPOLOGY = _register(
    "TopologyUnsatisfiable", "topology",
    "every allowed domain is at its skew ceiling or out of capacity")
CAPACITY = _register(
    "CapacityExhausted", "fit",
    "every compatible node/instance-type combination is exhausted or "
    "over limits")
NO_INSTANCE_TYPES = _register(
    "NoInstanceTypes", "compat",
    "no purchasable instance types and existing capacity is full")
NO_SURVIVING_TYPE = _register(
    "NoSurvivingType", "fit",
    "no instance type survives the node's accumulated requirements")
MIN_VALUES = _register(
    "MinValuesViolated", "compat",
    "the surviving type set exposes fewer distinct label values than "
    "the nodepool's minValues")
# oracle verdicts (scheduling/oracle.py)
POOL_LIMIT = _register(
    "PoolLimitExceeded", "limit",
    "a binding nodepool limit blocked the placement (oracle authority)")
# gang scheduling verdicts (ISSUE 15): emitted by BOTH engines — the
# kernel's _unsched_reason (solver/solve.py) and the oracle's atomic
# gang pre-pass (scheduling/oracle.py) — always for the WHOLE gang
# (atomicity: one member's verdict is every member's verdict)
GANG_PARTIAL = _register(
    "GangPartiallyPlaceable", "gang",
    "the best adjacency domain can hold some but not all gang members "
    "— the gang strands whole rather than split (tree carries the "
    "nearest domain and the deficit)")
GANG_DOMAIN = _register(
    "GangDomainExhausted", "gang",
    "no adjacency domain can currently hold any gang member — every "
    "eligible domain is out of capacity or ineligible")
GANG_TOO_LARGE = _register(
    "GangTooLarge", "gang",
    "the gang's member count exceeds what any single adjacency domain "
    "could hold even on an empty fleet at the solver's node ceiling")
GANG_INCOMPLETE = _register(
    "GangIncomplete", "gang",
    "the pending member count (plus members already bound on live "
    "nodes) does not match the gang-size annotation (fewer: placement "
    "waits for the full gang; more: fix gang-size — an over-full gang "
    "never self-heals by waiting)")
GANG_CODES = frozenset((GANG_PARTIAL, GANG_DOMAIN, GANG_TOO_LARGE,
                        GANG_INCOMPLETE))
# priority & preemption verdicts (ISSUE 16): emitted by the decode
# reclassification (solver/solve.py), the preemption planner
# (solver/preempt.py), and the preemption controller
# (controllers/preemption.py) — all held to the reason-literal gate.
PRIORITY_BAND_EXHAUSTED = _register(
    "PriorityBandExhausted", "priority",
    "capacity ran out inside this pod's priority band while at least "
    "one strictly-lower-priority group still placed — the preemption "
    "planner's trigger condition (kernel witness: the priority aux row)")
PREEMPTED_FOR = _register(
    "PreemptedFor", "priority",
    "this pod is a planned preemption victim: its (atomic, whole-gang "
    "when ganged) eviction seats a stranded strictly-higher-priority "
    "pod named in the preempted-for annotation")
PREEMPTION_INSUFFICIENT = _register(
    "PreemptionInsufficient", "priority",
    "evicting every evictable strictly-lower-priority victim still "
    "could not seat the stranded pod — preemption cannot help; the "
    "pod waits for capacity")
PRIORITY_CODES = frozenset((PRIORITY_BAND_EXHAUSTED, PREEMPTED_FOR,
                            PREEMPTION_INSUFFICIENT))
LEGACY = "Legacy"  # unregistered plain-string reason (should not occur)

# -- disruption decision vocabulary (ISSUE 14): the controllers'
# -- fleet-mutating decisions and their rejection verdicts, registered
# -- here so the decision ledger stores CODES and the kt-lint
# -- reason-literal gate can hold controllers/disruption.py to the same
# -- no-bare-strings contract as the unschedulability emitters.
# -- Constraint "none": these classify decisions, not pod eliminations.
CAPACITY_LAUNCHED = _register(
    "CapacityLaunched", "none",
    "provisioning launched new capacity for pending pods")
CONSOLIDATION_DELETE = _register(
    "ConsolidationDelete", "none",
    "consolidation deleted candidates whose pods fit on the remaining "
    "fleet (pure delete — always saves money)")
CONSOLIDATION_REPLACE = _register(
    "ConsolidationReplace", "none",
    "consolidation replaced candidates with one strictly cheaper node")
DRIFT_REPLACED = _register(
    "DriftReplaced", "none",
    "drifted capacity was replaced in kind (no cheaper-price "
    "requirement)")
NODE_EXPIRED = _register(
    "NodeExpired", "none",
    "the claim outlived its NodePool expireAfter and was deleted")
INTERRUPTION_RECLAIM = _register(
    "InterruptionReclaim", "none",
    "a cloud interruption signal (spot reclaim, maintenance, state "
    "change) deleted the claim ahead of the reclaim")
NODE_TERMINATED = _register(
    "NodeTerminated", "none",
    "the drained instance was released — the point the fleet $/hr "
    "actually falls for a prior delete/replace decision")
# rejection verdicts: why a consolidation candidate stayed up
REPLACEMENT_NOT_CHEAPER = _register(
    "ReplacementNotCheaper", "none",
    "the cheapest feasible replacement would not reduce fleet cost")
SPOT_TO_SPOT_DISABLED = _register(
    "SpotToSpotDisabled", "none",
    "spot-to-spot consolidation is behind a disabled feature gate")
SPOT_FLEXIBILITY_TOO_LOW = _register(
    "SpotFlexibilityTooLow", "none",
    "the spot replacement keeps too few instance types for reliable "
    "spot capacity (the >=15-types rule)")
CANDIDATE_NOT_RESCHEDULABLE = _register(
    "CandidateNotReschedulable", "none",
    "the candidate's pods cannot reschedule onto remaining capacity or "
    "an admissible replacement")
BUDGET_BLOCKED = _register(
    "DisruptionBudgetBlocked", "none",
    "a NodePool disruption budget (possibly cron-windowed) blocked the "
    "decision this pass")
NODEPOOL_DRIFT = _register(
    "NodePoolDrift", "none",
    "the claim's stamped NodePool hash no longer matches the live pool")

# delta-seam fallback vocabulary (solver/solve.py _delta_fallback /
# solver/delta.py plan+build): every non-engaged delta pass names one of
# these — an unknown reason is a registry violation, not a new string
DELTA_FALLBACK_REASONS = frozenset((
    "cold", "nodes", "price-cap", "limits", "small", "topology",
    "bucket", "seed", "slots", "stranded", "shape", "gang",
    # priority bands / preemption plans force a full pass until
    # seeded-merge support lands (ISSUE 16): band order is global, so a
    # delta-merged placement could seat a late low-priority group ahead
    # of an earlier-stranded higher band
    "priority", "preempt"))

# speculative-chunk seam fallback vocabulary (solver/solve.py
# _spec_fallback, ISSUE 19): same registry discipline as the delta
# seam's — every non-engaged spec pass names one of these.  A subset of
# the delta vocabulary plus nothing new: the spec path's exactness
# gates are the delta path's (topology-free, limit-free, single-band,
# gang-free) applied to the live encoding instead of a cached record
SPEC_FALLBACK_REASONS = frozenset((
    "small", "bucket", "topology", "shape", "gang", "priority",
    "price-cap", "limits", "slots", "stranded", "seed"))

# incremental-index seam fallback vocabulary (solver/solve.py
# _incr_fallback / solver/incr.py build_groups, ISSUE 20): every pass
# where the event-driven group index could have engaged but resolved
# the dirty set by walking instead names one of these.  Deliberately
# DISJOINT in meaning from the delta vocabulary — an index fallback
# degrades only the GROUPING to the O(cluster) walk; the delta seam
# then makes its own engage/fallback call downstream:
#   cold  — no index yet (no record stored, or the record was raced
#           away by an invalidation mid-store and the index dropped)
#   flood — the watch buffer overflowed (or an all-dirty invalidation
#           arrived): every event-derived fact is suspect
#   drift — the index's pod census disagrees with the live input (a
#           mutation reached the solver without a watch event)
#   pods  — pod names were invalidated without their objects (a
#           name-only feed cannot update group membership)
#   nodes — node-shaped dirt the event-time absorber could not prove
#           harmless (bind/unbind, allocatable change, unknown
#           deletion) — the walk's value sweep is the authority
#   order — the index cannot prove the walk's group order (a new
#           group key, a band flip, or a non-monotone key sequence)
INCR_FALLBACK_REASONS = frozenset((
    "cold", "flood", "drift", "pods", "nodes", "order"))

# tenant-scheduler shed vocabulary (service/scheduler.py)
SHED_ADMISSION = "admission"
SHED_DEADLINE = "deadline"
SHED_REASONS = frozenset((SHED_ADMISSION, SHED_DEADLINE))

# per-nodepool cause vocabulary for the oracle's open-new cascade
# (scheduling/oracle.py `_open_new`): each blocked pool names exactly one
# of these in the reason tree
CAUSE_NO_TYPES = "NoInstanceTypes"
CAUSE_TAINTS = "TaintsNotTolerated"
CAUSE_UNKNOWN_LABEL = "UnknownLabel"
CAUSE_INCOMPATIBLE = "IncompatibleRequirements"
CAUSE_LIMITS = "LimitsExceeded"
CAUSE_NO_FIT = "NoFittingType"
CAUSE_TOPOLOGY = "TopologyUnsatisfiable"
POOL_CAUSES = frozenset((
    CAUSE_NO_TYPES, CAUSE_TAINTS, CAUSE_UNKNOWN_LABEL,
    CAUSE_INCOMPATIBLE, CAUSE_LIMITS, CAUSE_NO_FIT, CAUSE_TOPOLOGY))


class Reason(str):
    """An unschedulability reason: the legacy human-readable string (the
    `str` value — existing logs, events, and substring assertions keep
    working) plus the structured `.code` and an optional `.tree` (the
    per-group constraint-elimination breakdown).  Pickles across the
    solverd wire with both attributes intact."""

    def __new__(cls, code: str, detail: str, tree: Optional[dict] = None):
        s = super().__new__(cls, detail)
        s.code = code
        s.tree = tree
        return s

    def __reduce__(self):
        return (Reason, (self.code, str(self), self.tree))


def make(code: str, detail: str, tree: Optional[dict] = None) -> Reason:
    """The one constructor verdict emitters use.  Unregistered codes are
    a programming error — fail loudly at the emit site, not in a
    dashboard three weeks later."""
    if code not in REGISTRY:
        raise ValueError(f"unregistered reason code {code!r}")
    return Reason(code, detail, tree)


def code_of(reason) -> str:
    """The structured code of any reason value; plain strings (foreign /
    legacy producers) map to LEGACY rather than raising."""
    return getattr(reason, "code", LEGACY)


def constraint_of(code: str) -> str:
    spec = REGISTRY.get(code)
    return spec.constraint if spec is not None else "none"


# -- the gate --------------------------------------------------------------
MODE_OFF, MODE_COUNTS, MODE_FULL = 0, 1, 2
_ENV = "KARPENTER_TPU_EXPLAIN"
_MODE_NAMES = {MODE_OFF: "off", MODE_COUNTS: "counts", MODE_FULL: "full"}


def mode() -> int:
    """KARPENTER_TPU_EXPLAIN=off|counts|full (default counts; this
    module is the knob's single grammar owner).  Malformed values
    degrade to the default, never crash."""
    raw = os.environ.get(_ENV, "").strip().lower()
    if raw in ("off", "0", "false", "no", "none"):
        return MODE_OFF
    if raw == "full":
        return MODE_FULL
    return MODE_COUNTS


def mode_name(m: int) -> str:
    return _MODE_NAMES.get(m, "counts")


# -- explain engine --------------------------------------------------------
def counts_dict(enc, out, gi: int) -> Dict[str, int]:
    """One group's per-constraint elimination counts as {constraint:
    n_columns}: kernel aux when the solve carried it
    (`out["explain_counts"]`, KERNEL_CONSTRAINTS order), host recompute
    otherwise; the host-owned classes (compat, price) come from the
    encode-side counts (`enc.explain_host`) when armed, else from the
    final group mask alone (price folded into compat)."""
    import numpy as np
    counts: Dict[str, int] = {}
    host = getattr(enc, "explain_host", None)
    O = enc.n_columns
    if host is not None and gi < len(host):
        counts["compat"] = int(host[gi][0])
        counts["price"] = int(host[gi][1])
    else:
        counts["compat"] = int(O - np.asarray(
            enc.group_mask[gi], dtype=bool).sum())
        counts["price"] = 0
    kc = out.get("explain_counts") if isinstance(out, dict) else None
    if kc is not None and gi < len(kc):
        row = np.asarray(kc[gi])
        for i, name in enumerate(KERNEL_CONSTRAINTS):
            counts[name] = int(row[i])
    else:
        counts.update(host_counts(enc, out, gi))
    return counts


def host_counts(enc, out, gi: int) -> Dict[str, int]:
    """Numpy mirror of the kernel's aux counts for one group, computed
    against the FINAL solve state visible on the host.  Used when the
    dispatch path carried no aux (batched/sweep kernels, replay of a
    pre-explain capture) — per stranded group only, so the cost is
    bounded by the strand count, not the problem size.

    `limit` is computed against the INITIAL pool limits (the kernel's
    final budgets are not downloaded): a column counts as limit-blocked
    when its pool's configured limit cannot fund even one pod on an
    otherwise-empty budget — a lower bound on the kernel's final-state
    verdict, honest for the "is a finite limit involved at all"
    question the tree answers."""
    import numpy as np
    gmask = np.asarray(enc.group_mask[gi], dtype=bool)
    req = np.asarray(enc.group_req[gi], dtype=np.float32)
    alloc = np.asarray(enc.col_alloc, dtype=np.float32)
    daemon = np.asarray(enc.col_daemon, dtype=np.float32)
    avail = alloc - daemon - req[None, :]
    fits = np.all(avail >= -1e-3, axis=-1)
    out_c: Dict[str, int] = {
        "fit": int((gmask & ~fits).sum()),
    }
    # limit: columns of pools whose configured limit can't fund one pod
    pool_limit = np.asarray(enc.pool_limit, dtype=np.float32)
    col_pool = np.asarray(enc.col_pool)
    lim_ok = np.all(
        pool_limit[col_pool] - daemon - req[None, :] >= -1e-3, axis=-1)
    out_c["limit"] = int((gmask & fits & ~lim_ok).sum())
    # topology: only meaningful when the group carried a dynamic domain
    # constraint — blocked domains from the final dom_placed rows
    topo = 0
    dsel = int(enc.group_dsel[gi]) if enc.group_dsel is not None else 0
    if dsel and isinstance(out, dict) and "dom_placed" in out:
        D = enc.n_domains
        dbase = np.asarray(enc.group_dbase[gi][:D], dtype=np.int64)
        placed = np.asarray(out["dom_placed"][gi][:D], dtype=np.int64)
        elig = np.asarray(enc.group_delig[gi][:D], dtype=bool)
        f = dbase + placed
        skew = int(enc.group_skew[gi])
        m = int(f[elig].min()) if elig.any() else 0
        if enc.group_mindom[gi] > 0 and \
                int((f[elig] > 0).sum()) < int(enc.group_mindom[gi]):
            m = 0
        blocked = (~elig) | (f >= m + skew)
        dom_ids = np.asarray(
            enc.col_zone if dsel == 1 else enc.col_ct)
        dom_clipped = np.clip(dom_ids, 0, D - 1)
        topo = int((gmask & blocked[dom_clipped]).sum())
    out_c["topology"] = topo
    whole = bool(enc.group_whole_node is not None
                 and enc.group_whole_node[gi])
    stranded = bool(isinstance(out, dict) and "unsched" in out
                    and out["unsched"][gi] > 0)
    out_c["whole_node"] = int(gmask.sum()) if whole and stranded else 0
    slots = 0
    if isinstance(out, dict) and "num_active" in out and stranded:
        na = int(out["num_active"])
        n_axis = out["take_new"].shape[1] if "take_new" in out else 0
        slots = int(n_axis > 0 and na >= n_axis)
    out_c["slots"] = slots
    return out_c


def nearest_miss(enc, gi: int) -> Optional[dict]:
    """The closest eliminated catalog column and what would unblock it:
    the masked-in column with the smallest worst-resource deficit for a
    fit miss, or — when a price cap was folded into the mask
    (`enc.explain_price_cap`) — the cheapest FITTING column above the
    cap for a price miss (label compatibility is not re-derivable once
    the cap is folded in, so the price candidate is capacity-checked
    only).  Host numpy over [O] — called per stranded group only."""
    import numpy as np
    O = enc.n_columns
    if O == 0:
        return None
    req = np.asarray(enc.group_req[gi], dtype=np.float32)
    alloc = np.asarray(enc.col_alloc, dtype=np.float32)
    daemon = np.asarray(enc.col_daemon, dtype=np.float32)
    deficit = np.clip(req[None, :] - (alloc - daemon), 0.0, None)  # [O,R]
    worst = deficit.max(axis=-1)                                   # [O]
    gmask = np.asarray(enc.group_mask[gi], dtype=bool)
    cand = gmask & (worst > 0)
    if cand.any():
        # the masked-in column with the smallest worst-resource deficit
        idx = int(np.where(cand, worst, np.inf).argmin())
        col = enc.columns[idx]
        from karpenter_tpu.models.resources import RESOURCE_AXIS
        by_res = {RESOURCE_AXIS[r]: round(float(deficit[idx][r]), 3)
                  for r in range(len(RESOURCE_AXIS))
                  if deficit[idx][r] > 0}
        return {"constraint": "fit", "instance_type": col.type_name,
                "nodepool": col.pool, "zone": col.zone,
                "deficit": by_res}
    cap = getattr(enc, "explain_price_cap", None)
    if cap is not None and enc.col_price is not None:
        price = np.asarray(enc.col_price, dtype=np.float64)
        over = (~gmask) & (price >= cap) & (worst <= 0)
        if over.any():
            idx = int(np.where(over, price, np.inf).argmin())
            col = enc.columns[idx]
            return {"constraint": "price",
                    "instance_type": col.type_name,
                    "nodepool": col.pool, "zone": col.zone,
                    "price": round(float(price[idx]), 6),
                    "price_cap": round(float(cap), 6)}
    return None


def _suggestion(counts: Dict[str, int], enc, gi: int,
                miss: Optional[dict]) -> Optional[str]:
    """The operator-facing 'what change would unblock it' line, from the
    dominant constraint class."""
    import numpy as np
    if counts.get("limit"):
        finite = [p.meta.name for pi, p in enumerate(enc.pools)
                  if np.isfinite(np.asarray(enc.pool_limit[pi])).any()]
        if finite:
            return ("raise the limit on nodepool "
                    + " or ".join(sorted(finite)))
        return "raise the binding nodepool limit"
    if counts.get("price"):
        if miss is not None and miss.get("constraint") == "price":
            return (f"raise the price cap to >= {miss['price']} "
                    f"({miss['instance_type']} is the cheapest fitting "
                    "column above it)")
        return "raise the price cap (columns were eliminated on price)"
    if counts.get("topology"):
        return ("add capacity in an under-ceiling domain or relax "
                "maxSkew")
    if counts.get("slots"):
        return "raise the solver's max_nodes ceiling"
    if counts.get("whole_node"):
        return ("no single node holds the whole co-located group — "
                "larger instance types or fewer members")
    if miss is not None:
        need = ", ".join(f"{k}+{v}" for k, v in
                         sorted(miss["deficit"].items()))
        return (f"nearest miss {miss['instance_type']}: needs {need} "
                "more allocatable")
    if counts.get("compat"):
        return ("no compatible column at all — check nodepool "
                "requirements/taints against the pod")
    return None


def _map_detail(enc, out, gi: int, limit: int = 5) -> Optional[dict]:
    """The full-mode [G, O] class map rendered as named columns: per
    kernel constraint class, up to `limit` example catalog columns it
    eliminated — the "which columns exactly" answer counts cannot give
    (present only under KARPENTER_TPU_EXPLAIN=full / replay)."""
    import numpy as np
    m = out.get("explain_map") if isinstance(out, dict) else None
    if m is None or gi >= len(m):
        return None
    row = np.asarray(m[gi][:enc.n_columns])
    detail: Dict[str, list] = {}
    for ci, name in enumerate(KERNEL_CONSTRAINTS):
        idxs = np.nonzero(row == ci + 1)[0]
        if not len(idxs):
            continue
        detail[name] = [
            {"instance_type": enc.columns[int(i)].type_name,
             "zone": enc.columns[int(i)].zone,
             "capacity_type": enc.columns[int(i)].capacity_type}
            for i in idxs[:limit]]
        if len(idxs) > limit:
            detail[name].append({"and_more": int(len(idxs) - limit)})
    return detail or None


def build_tree(enc, out, gi: int, code: str) -> dict:
    """One stranded group's reason tree: per-constraint elimination
    counts over the catalog columns, the per-nodepool compatibility
    verdicts, the nearest-miss type, and the unblock suggestion; under
    full mode, also the per-column eliminated-columns detail."""
    counts = counts_dict(enc, out, gi)
    pools = []
    merged = enc.merged_reqs[gi] if gi < len(enc.merged_reqs) else []
    for pidx, pool in enumerate(enc.pools):
        verdict = ("incompatible or taints"
                   if pidx < len(merged) and merged[pidx] is None
                   else "compatible")
        pools.append({"nodepool": pool.meta.name, "verdict": verdict})
    miss = nearest_miss(enc, gi)
    tree = {
        "code": code,
        "constraint": constraint_of(code),
        "group": gi,
        "pods": int(enc.group_count[gi]) if gi < len(enc.group_count)
        else None,
        "unplaced": (int(out["unsched"][gi])
                     if isinstance(out, dict) and "unsched" in out
                     and gi < len(out["unsched"]) else None),
        "columns_total": enc.n_columns,
        "eliminations": counts,
        "pools": pools,
    }
    if miss is not None:
        tree["nearest_miss"] = miss
    sug = _suggestion(counts, enc, gi, miss)
    if sug is not None:
        tree["suggestion"] = sug
    cols = _map_detail(enc, out, gi)
    if cols is not None:
        tree["eliminated_columns"] = cols
    return tree


# -- the per-process provenance store -------------------------------------
class ExplainStore:
    """Bounded pod → explain-entry map, the `GET /debug/explain`
    backing: the provisioning controller registers every final
    unschedulable verdict (local, degraded, or remote — the tree rides
    the pickled `Reason`), newest entry wins per (pod, trace)."""

    def __init__(self, capacity: int = 512, per_pod: int = 4):
        self._lock = threading.Lock()
        self.capacity = capacity
        self.per_pod = per_pod
        self._by_pod: "Dict[str, List[dict]]" = {}
        self._order: List[str] = []   # insertion order for eviction

    def register(self, unschedulable: Dict[str, str],
                 trace_id: Optional[str] = None,
                 source: str = "local") -> int:
        n = 0
        # debug-surface timestamp (GET /debug/explain freshness / TTL
        # eviction only): never part of a solve output or digest
        now = time.time()  # kt-lint: disable=nondeterminism-source
        with self._lock:
            for pod, reason in unschedulable.items():
                entry = {
                    "pod": pod,
                    "ts": now,
                    "trace_id": trace_id,
                    "source": source,
                    "code": code_of(reason),
                    "constraint": constraint_of(code_of(reason)),
                    "detail": str(reason),
                    "tree": getattr(reason, "tree", None),
                }
                rows = self._by_pod.get(pod)
                if rows is None:
                    rows = self._by_pod[pod] = []
                else:
                    # LRU, not first-insertion order: a chronically
                    # re-stranded pod holds the NEWEST verdict and must
                    # neither be evicted before colder pods nor drop out
                    # of the recent() listing
                    self._order.remove(pod)
                self._order.append(pod)
                rows.insert(0, entry)
                del rows[self.per_pod:]
                n += 1
            while len(self._order) > self.capacity:
                self._by_pod.pop(self._order.pop(0), None)
        return n

    def lookup(self, pod: str,
               trace_id: Optional[str] = None) -> Optional[dict]:
        with self._lock:
            rows = self._by_pod.get(pod)
            if not rows:
                return None
            if trace_id is not None:
                for e in rows:
                    if e["trace_id"] == trace_id:
                        return dict(e)
                return None
            return dict(rows[0])

    def recent(self, limit: int = 32) -> List[dict]:
        if limit <= 0:
            return []  # order[-0:] would be the whole list, not nothing
        with self._lock:
            pods = self._order[-limit:]
            return [
                {k: self._by_pod[p][0][k]
                 for k in ("pod", "ts", "trace_id", "code", "constraint")}
                for p in reversed(pods) if self._by_pod.get(p)]

    def size(self) -> int:
        with self._lock:
            return len(self._by_pod)

    def reset(self) -> None:
        with self._lock:
            self._by_pod.clear()
            self._order.clear()


STORE = ExplainStore()


def event_message(reason) -> str:
    """`cluster.record_event` message form: code + the legacy detail —
    '[Code] detail' when structured, the plain string otherwise."""
    code = code_of(reason)
    if code == LEGACY:
        return str(reason)
    return f"[{code}] {reason}"


def reason_table() -> List[dict]:
    """The registry as rows (docs/CLI rendering)."""
    return [{"code": s.code, "constraint": s.constraint,
             "summary": s.summary}
            for s in sorted(REGISTRY.values(), key=lambda s: s.code)]


def constraint_help(name: str) -> str:
    return _CONSTRAINT_HELP.get(name, "")
