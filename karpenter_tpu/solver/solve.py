"""TPUSolver — drop-in replacement for the oracle behind the Solve() seam.

encode (host, numpy) → solve_ffd (device, one XLA program) → decode (host).
Shapes are padded to buckets so repeat calls hit the jit cache.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from karpenter_tpu.models import wellknown
from karpenter_tpu.models.objects import Pod
from karpenter_tpu.models.requirements import Requirement, Requirements
from karpenter_tpu.models.resources import RESOURCE_AXIS, Resources
from karpenter_tpu.scheduling.types import (
    NewNodeClaim,
    ScheduleInput,
    ScheduleResult,
    min_values_violation,
)
from karpenter_tpu.solver import ffd
from karpenter_tpu.solver.encode import EncodedProblem, bucket, encode

R = len(RESOURCE_AXIS)

G_BUCKETS = (8, 32, 128, 512, 2048)
E_BUCKETS = (0, 64, 512, 4096)
O_ALIGN = 512


class UnsupportedPods(Exception):
    """Raised when the encoding can't express some pods' constraints yet;
    the provisioner falls back to the CPU oracle for this batch."""


def _supported(pod: Pod) -> bool:
    if pod.topology_spread:
        return False
    if any(t.required for t in pod.pod_affinities):
        return False
    return True


class TPUSolver:
    def __init__(self, max_nodes: int = 1024):
        self.max_nodes = max_nodes
        self._cat_key = None
        self._cat = None

    def _catalog_encoding(self, inp: ScheduleInput):
        """Cache the catalog-side encoding + its device-resident padded
        arrays. The instance-type provider returns the identical list object
        until a seqnum changes (instancetype.py cache discipline), so object
        identity is the invalidation signal."""
        from karpenter_tpu.solver.encode import encode_catalog
        pools = sorted(inp.nodepools, key=lambda p: (-p.weight, p.meta.name))
        # hold STRONG references to the cached lists: identity (`is`) is then
        # a sound invalidation signal — a freed list's address could be
        # recycled, but a referenced one cannot be
        lists = tuple(inp.instance_types.get(p.name) for p in pools)
        key = (
            lists,
            # static_hash covers the template; name+weight cover identity and
            # priority order, which the hash deliberately excludes
            tuple((p.meta.name, p.weight, p.static_hash()) for p in pools),
            tuple(sorted((k, tuple(v.v)) for k, v in inp.daemon_overhead.items())),
        )
        def _same(a, b):
            return (a is not None and b is not None
                    and len(a[0]) == len(b[0])
                    and all(x is y for x, y in zip(a[0], b[0]))
                    and a[1:] == b[1:])
        if not _same(key, self._cat_key):
            self._cat = encode_catalog(inp)
            self._cat_key = key
            cat = self._cat
            O = -(-len(cat.columns) // O_ALIGN) * O_ALIGN
            import jax
            cat.device_args = dict(
                col_alloc=jax.device_put(self._pad(cat.col_alloc, 0, O)),
                col_daemon=jax.device_put(self._pad(cat.col_daemon, 0, O)),
                col_pool=jax.device_put(self._pad(cat.col_pool, 0, O)),
                pool_daemon=jax.device_put(cat.pool_daemon),
                O=O,
            )
        return self._cat

    # -- padding ---------------------------------------------------------
    @staticmethod
    def _pad(arr: np.ndarray, axis: int, to: int, value=0) -> np.ndarray:
        pad = to - arr.shape[axis]
        if pad <= 0:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, pad)
        return np.pad(arr, widths, constant_values=value)

    def solve(self, inp: ScheduleInput) -> ScheduleResult:
        unsupported = [p for p in inp.pods if not _supported(p)]
        if unsupported:
            raise UnsupportedPods(
                f"{len(unsupported)} pods carry topology/affinity constraints "
                "not yet encoded for the device solver")

        cat = self._catalog_encoding(inp)
        enc = encode(inp, cat)
        if enc.n_groups == 0:
            return ScheduleResult()
        if enc.n_columns == 0:
            # no purchasable capacity — but existing nodes can still absorb
            # pods, exactly as the oracle fills them first
            return self._existing_only(enc)

        G = bucket(enc.n_groups, G_BUCKETS)
        E = bucket(len(enc.existing), E_BUCKETS)
        dev = cat.device_args
        O = dev["O"]

        packed = ffd.solve_ffd(
            self._pad(enc.group_req, 0, G),
            self._pad(enc.group_count, 0, G),
            self._pad(self._pad(enc.group_mask, 1, O), 0, G),
            self._pad(self._pad(enc.exist_mask, 1, E), 0, G),
            self._pad(enc.exist_remaining, 0, E),
            dev["col_alloc"],
            dev["col_daemon"],
            dev["col_pool"],
            dev["pool_daemon"],
            enc.pool_limit,
            max_nodes=self.max_nodes,
        )
        out = ffd.unpack(packed, G, E, self.max_nodes, R)
        return self._decode(enc, out)

    def _existing_only(self, enc: EncodedProblem) -> ScheduleResult:
        """Host-side step-1-only fill when there are no columns to buy."""
        res = ScheduleResult()
        remaining = enc.exist_remaining.copy()
        for gi, pods in enumerate(enc.groups):
            req = enc.group_req[gi]
            cursor = 0
            for ei in range(len(enc.existing)):
                if cursor >= len(pods) or not enc.exist_mask[gi, ei]:
                    continue
                with np.errstate(divide="ignore", invalid="ignore"):
                    per = np.where(req > 0, np.floor((remaining[ei] + 1e-3) / np.where(req > 0, req, 1)), np.inf)
                k = int(min(np.min(per), len(pods) - cursor))
                if k <= 0:
                    continue
                for pod in pods[cursor:cursor + k]:
                    res.existing_assignments[pod.meta.name] = enc.existing[ei].name
                remaining[ei] -= k * req
                cursor += k
            for pod in pods[cursor:]:
                res.unschedulable[pod.meta.name] = "no instance types available"
        return res

    # -- decode ----------------------------------------------------------
    def _decode(self, enc: EncodedProblem, out: Dict[str, np.ndarray]) -> ScheduleResult:
        res = ScheduleResult()
        Gr = enc.n_groups
        Er = len(enc.existing)
        num_active = int(out["num_active"])

        take_exist = out["take_exist"][:Gr, :Er].astype(int)
        take_new = out["take_new"][:Gr, : self.max_nodes].astype(int)
        unsched = out["unsched"][:Gr].astype(int)
        node_pool = out["node_pool"]
        used = out["used"]
        # reconstruct each active node's surviving-column mask host-side
        # (cheap numpy; saves shipping the [N,O] device array back):
        #   columns of the node's pool ∩ every resident group's label mask
        #   ∩ capacity ≥ final used
        col_pool = enc.col_pool
        col_alloc = enc.col_alloc

        # distribute each group's pods: existing nodes first (scan order),
        # then new nodes, then unschedulable — matching kernel accounting
        node_pods: Dict[int, List[Pod]] = {}
        node_groups: Dict[int, List[int]] = {}
        for gi, pods in enumerate(enc.groups):
            cursor = 0
            for ei in range(Er):
                k = take_exist[gi, ei]
                for pod in pods[cursor:cursor + k]:
                    res.existing_assignments[pod.meta.name] = enc.existing[ei].name
                cursor += k
            for ni in range(num_active):
                k = take_new[gi, ni]
                if k:
                    node_pods.setdefault(ni, []).extend(pods[cursor:cursor + k])
                    node_groups.setdefault(ni, []).append(gi)
                    cursor += k
            for pod in pods[cursor:cursor + unsched[gi]]:
                res.unschedulable[pod.meta.name] = self._unsched_reason(enc, gi)

        # claim metadata (requirements + ranked type list) depends only on
        # (pool, resident groups, used vector) — hundreds of nodes from the
        # same fill collapse to a handful of distinct computations
        claim_cache: Dict[tuple, tuple] = {}
        for ni in range(num_active):
            pods = node_pods.get(ni, [])
            if not pods:
                continue
            pidx = int(node_pool[ni])
            pool = enc.pools[pidx]
            gis = tuple(node_groups.get(ni, []))
            ckey = (pidx, gis, used[ni].tobytes())
            cached = claim_cache.get(ckey)
            if cached is None:
                nmask = (col_pool == pidx) & np.all(
                    col_alloc - used[ni][None, :R] >= -1e-3, axis=-1)
                for gi in gis:
                    nmask &= enc.group_mask[gi]
                idxs = np.nonzero(nmask)[0]
                if len(idxs) == 0:
                    cached = ("no surviving instance type", None, None, None)
                else:
                    reqs = pool.template_requirements()
                    for gi in gis:
                        merged = enc.merged_reqs[gi][pidx]
                        if merged is not None:
                            reqs = reqs.intersection(merged)
                    best_price: Dict[str, float] = {}
                    type_of: Dict[str, object] = {}
                    for ci in idxs:
                        c = enc.columns[ci]
                        if c.price < best_price.get(c.type_name, float("inf")):
                            best_price[c.type_name] = c.price
                            type_of[c.type_name] = c.instance_type
                    ranked = sorted(best_price, key=lambda t: (best_price[t], t))
                    violation = min_values_violation(
                        reqs, [type_of[t] for t in ranked])
                    cached = (violation, reqs, ranked, best_price)
                claim_cache[ckey] = cached
            violation, reqs, ranked, best_price = cached
            if violation is not None:
                for pod in pods:
                    res.unschedulable[pod.meta.name] = violation
                continue
            res.new_claims.append(NewNodeClaim(
                nodepool=pool.name,
                node_class_ref=pool.node_class_ref,
                requirements=reqs,
                pods=pods,
                requests=Resources(list(used[ni][:R].astype(float))),
                instance_type_names=ranked,
                price=best_price[ranked[0]],
                taints=list(pool.taints),
                startup_taints=list(pool.startup_taints),
                hostname=f"tpu-solver-node-{ni}",
            ))
        return res

    @staticmethod
    def _unsched_reason(enc: EncodedProblem, gi: int) -> str:
        if not enc.group_mask[gi].any() and not enc.exist_mask[gi].any():
            details = []
            for pidx, pool in enumerate(enc.pools):
                if enc.merged_reqs[gi][pidx] is None:
                    details.append(f"nodepool {pool.name}: incompatible or taints")
                else:
                    details.append(f"nodepool {pool.name}: no instance type fits/compatible")
            return "no nodepool can schedule pod: " + "; ".join(details)
        return ("no capacity: every compatible node/instance-type " +
                "combination is exhausted or over limits")
